"""Collective types and options.

Parity with ``python/ray/util/collective/types.py``: ``Backend`` and
``ReduceOp`` enums plus per-op options dataclasses.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import List


class Backend:
    """Collective backend names. The reference supports NCCL/GLOO and rejects
    MPI (``collective.py:59-60``); here the tensor plane is XLA — collectives
    compile onto ICI — with a CPU (numpy) backend for host tensors and tests.
    NCCL/GLOO names are accepted as aliases so reference code ports run."""

    XLA = "xla"
    CPU = "cpu"

    _ALIASES = {"nccl": XLA, "gloo": CPU, "xla": XLA, "cpu": CPU}

    def __new__(cls, name: str = "xla"):
        backend = cls._ALIASES.get(str(name).lower())
        if backend is None:
            if str(name).lower() == "mpi":
                raise ValueError("MPI backend is not supported")
            raise ValueError(f"unknown collective backend {name!r}; "
                             f"use 'xla' or 'cpu'")
        return backend


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3


@dataclass(frozen=True)
class CollectiveConfig:
    """Per-group collective tuning, fixed at group creation.

    ``compression`` selects the wire scheme for allreduce/reducescatter
    payloads: ``"q8"`` (block-wise symmetric int8), ``"fp8"``
    (float8_e4m3fn blocks) or ``"none"``. ``quant_block_bytes`` is the
    *input* bytes per scale block — smaller blocks track local dynamic
    range tighter at more scale overhead (at 256 an f32 tensor ships at
    ~0.27x wire). ``ranks_per_host`` > 1 turns on the two-level
    hierarchical decomposition: contiguous rank spans of that size form
    a "host" whose intra-host reduction runs at full precision (the
    in-process/ICI hop), and only the per-host partials cross the
    expensive inter-host seam quantized.

    The (scheme, block) pair is folded into every rank's collective
    fingerprint, so ranks joining one group with different configs fail
    with :class:`~ray_tpu.observability.comms.CollectiveDivergenceError`
    instead of corrupting the reduction.
    """

    compression: str = "none"
    quant_block_bytes: int = 256
    ranks_per_host: int = 0

    def __post_init__(self):
        if self.compression not in ("none", "q8", "fp8"):
            raise ValueError(
                f"compression must be 'none', 'q8' or 'fp8', got "
                f"{self.compression!r}")
        if self.quant_block_bytes < 16:
            raise ValueError(
                f"quant_block_bytes must be >= 16 (one f32 scale per "
                f"block caps useful overhead), got {self.quant_block_bytes}")
        if self.ranks_per_host < 0:
            raise ValueError(
                f"ranks_per_host must be >= 0, got {self.ranks_per_host}")


unset_timeout_ms = 30000


@dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = unset_timeout_ms


@dataclass
class BarrierOptions:
    timeout_ms: int = unset_timeout_ms


@dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = unset_timeout_ms


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = unset_timeout_ms


@dataclass
class AllGatherOptions:
    timeout_ms: int = unset_timeout_ms


@dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = unset_timeout_ms


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = unset_timeout_ms


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = unset_timeout_ms
