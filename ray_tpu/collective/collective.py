"""Collective communication API.

Parity with ``python/ray/util/collective/collective.py``: the same group API
(``init_collective_group`` :120, ``create_collective_group`` :151-212,
``allreduce`` :258, ``barrier`` :298, ``reduce`` :311, ``broadcast`` :373,
``allgather`` :423, ``reducescatter`` :472, ``send/recv`` :531,594,
``destroy_collective_group`` :216) with backends ``xla`` (ICI-compiled
collectives) and ``cpu`` (numpy). Group state lives in a process-global
registry — the host-granular analogue of the reference's per-process
``GroupManager`` + named-``Info``-actor rendezvous (``collective.py:40-112``).
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.collective.collective_group.cpu_group import (CPUGroup,
                                                           CPUGroupShared)
from ray_tpu.collective.collective_group.xla_group import (XLAGroup,
                                                           XLAGroupShared)
from ray_tpu.collective.types import Backend, CollectiveConfig, ReduceOp

_registry_lock = threading.Lock()
_shared_groups: Dict[str, Any] = {}        # group_name -> Shared state  # raylint: guarded-by(_registry_lock)
_local_groups = threading.local()          # per-caller rank-bound groups
_process_joined: set = set()               # process-level plane memberships  # raylint: guarded-by(_registry_lock)


def _spans_processes() -> bool:
    """True when this caller is one rank of a PROCESS-spanning group: it
    runs inside a cluster daemon (DistributedRuntime executor), so sibling
    ranks live in other daemons and the in-process thread rendezvous can
    never see them. Drivers and single-process runtimes keep the
    thread-shared groups."""
    from ray_tpu._private import worker as _worker
    runtime = _worker.try_global_runtime()
    if runtime is None:
        return False
    from ray_tpu._private.distributed import DistributedRuntime
    return isinstance(runtime, DistributedRuntime) and not runtime.is_driver


class GroupManager:
    """Per-caller map of group_name -> rank-bound group object."""

    @staticmethod
    def _groups() -> Dict[str, Any]:
        if not hasattr(_local_groups, "groups"):
            _local_groups.groups = {}
        return _local_groups.groups

    @staticmethod
    def _resolve_config(config: Optional[CollectiveConfig]) -> CollectiveConfig:
        """Explicit config wins; otherwise the process-wide knobs decide
        (``collective_compression`` / ``quant_block_bytes`` /
        ``collective_ranks_per_host``), so a whole deployment can flip to
        q8 wire — or the autopilot's collective policy can flip it from
        ledgered busbw — without touching call sites."""
        if config is not None:
            return config
        from ray_tpu._private.config import _config
        return CollectiveConfig(
            compression=str(_config.get("collective_compression")),
            quant_block_bytes=int(_config.get("quant_block_bytes")),
            ranks_per_host=int(_config.get("collective_ranks_per_host")))

    @classmethod
    def create_group(cls, backend: str, world_size: int, rank: int,
                     group_name: str, devices: Optional[List] = None,
                     config: Optional[CollectiveConfig] = None):
        backend = Backend(backend)
        config = cls._resolve_config(config)
        if backend == Backend.XLA and devices is None and _spans_processes():
            # Rank-per-process group: ranks live in different daemon
            # processes, rendezvous through the state-service KV and the
            # JAX multi-controller runtime (the reference's NCCL-group
            # path, nccl_collective_group.py:127). Pass ``devices`` to
            # bind multiple ranks inside ONE daemon to local devices via
            # the thread-rendezvous group instead.
            from ray_tpu.collective.collective_group.xla_process_group import (
                XLAProcessGroup)
            with _registry_lock:
                if group_name in _process_joined:
                    raise RuntimeError(
                        f"a rank of group {group_name!r} already joined "
                        f"from this process; one process is one rank on "
                        f"the tensor plane (libtpu single-owner). Place "
                        f"one worker per host daemon, or pass devices= "
                        f"for an intra-process group.")
                _process_joined.add(group_name)
            g = XLAProcessGroup(world_size, rank, group_name, config=config)
            cls._groups()[group_name] = g
            return g
        with _registry_lock:
            shared = _shared_groups.get(group_name)
            if shared is None:
                if backend == Backend.XLA:
                    shared = XLAGroupShared(world_size, devices,
                                            label=group_name)
                else:
                    shared = CPUGroupShared(world_size, devices,
                                            label=group_name)
                shared.join_count = 0
                _shared_groups[group_name] = shared
            else:
                if shared.world_size != world_size:
                    raise ValueError(
                        f"group {group_name!r} exists with world_size="
                        f"{shared.world_size}, requested {world_size}")
                existing_backend = (Backend.XLA
                                    if isinstance(shared, XLAGroupShared)
                                    else Backend.CPU)
                if existing_backend != backend:
                    raise ValueError(
                        f"group {group_name!r} exists with backend "
                        f"{existing_backend!r}, requested {backend!r}")
            shared.join_count += 1
        group_cls = XLAGroup if isinstance(shared, XLAGroupShared) else CPUGroup
        g = group_cls(world_size, rank, group_name, shared, config=config)
        cls._groups()[group_name] = g
        return g

    @classmethod
    def get_group(cls, group_name: str):
        return cls._groups().get(group_name)

    @classmethod
    def destroy_group(cls, group_name: str):
        """Detach this caller; shared state is freed when the last rank
        leaves (a single rank's destroy must not split the group)."""
        g = cls._groups().pop(group_name, None)
        if g is None:
            return
        g.destroy()
        with _registry_lock:
            shared = _shared_groups.get(group_name)
            if shared is g._shared:
                shared.join_count -= 1
                if shared.join_count <= 0:
                    _shared_groups.pop(group_name, None)


def is_group_initialized(group_name: str = "default") -> bool:
    return GroupManager.get_group(group_name) is not None


def init_collective_group(world_size: int, rank: int, backend: str = "xla",
                          group_name: str = "default",
                          devices: Optional[List] = None,
                          config: Optional[CollectiveConfig] = None):
    """Join a collective group from inside an actor/task (collective.py:120)."""
    if world_size <= 0 or not (0 <= rank < world_size):
        raise ValueError(f"invalid world_size={world_size} rank={rank}")
    if is_group_initialized(group_name):
        raise RuntimeError(f"group {group_name!r} already initialized here")
    return GroupManager.create_group(backend, world_size, rank, group_name,
                                     devices, config)


def create_collective_group(actors: List, world_size: int,
                            ranks: List[int], backend: str = "xla",
                            group_name: str = "default",
                            devices: Optional[List] = None,
                            config: Optional[CollectiveConfig] = None):
    """Driver-side declarative setup (collective.py:151-212): instructs each
    actor to join the group with its assigned rank."""
    from ray_tpu._private import worker as _worker
    if len(actors) != world_size or sorted(ranks) != list(range(world_size)):
        raise ValueError("actors/ranks must cover 0..world_size-1")
    refs = [actor.__ray_collective_init__.remote(world_size, rank, backend,
                                                 group_name, devices, config)
            for actor, rank in zip(actors, ranks)]
    return _worker.get(refs)


def destroy_collective_group(group_name: str = "default"):
    GroupManager.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    g = GroupManager.get_group(group_name)
    return g.rank if g is not None else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = GroupManager.get_group(group_name)
    return g.world_size if g is not None else -1


def _group(group_name: str):
    g = GroupManager.get_group(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "actor/task; call init_collective_group first")
    return g


def _op_group(args: tuple, kwargs: dict) -> str:
    """Recover ``group_name`` from any collective signature: it is the
    only string positional (tensors, ranks and ReduceOps never are)."""
    return (kwargs.get("group_name")
            or next((a for a in args if isinstance(a, str)), "default"))


# numpy/jax dtype __str__ costs more than the whole ledger write; the
# distinct dtypes crossing the collective API are a handful, so memoize.
_dtype_strs: Dict[Any, str] = {}


def _dtype_str(dtype) -> str:
    try:
        s = _dtype_strs.get(dtype)
    except TypeError:               # unhashable dtype-like: stringify raw
        return str(dtype)
    if s is None:
        s = _dtype_strs[dtype] = str(dtype)
    return s


def _collective_wait(fn):
    """The single seam every collective op passes through.

    Attributes the blocking time to the goodput ledger's
    ``collective_wait`` category (first-trace compile inside the op
    opens a nested ``compile`` interval, which pauses this one — the
    exclusivity rule keeps the two from double-counting), records the
    completed op into the comms ledger (bytes / dtype / duration →
    algbw/busbw), and exposes the ``collective.op`` chaos injection
    point so a fault schedule can delay one rank into the rendezvous —
    the drill the comms plane's skew attribution must catch.
    """
    op_name = fn.__name__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        import time as _time
        from ray_tpu import chaos
        from ray_tpu.observability import comms, goodput, perf
        if chaos.ENABLED:
            group = _op_group(args, kwargs)
            chaos.inject("collective.op", group=group, op=op_name,
                         rank=str(get_rank(group)))
        if not comms.ENABLED:
            if not goodput.ENABLED:
                return fn(*args, **kwargs)
            with goodput.interval("collective_wait"):
                return fn(*args, **kwargs)
        group = _op_group(args, kwargs)
        t0 = _time.monotonic()
        if goodput.ENABLED:
            with goodput.interval("collective_wait"):
                result = fn(*args, **kwargs)
        else:
            result = fn(*args, **kwargs)
        dur = _time.monotonic() - t0
        # bytes/dtype come from the tensor argument when there is one
        # (never for barrier; recv reports its received tensor).
        obj = args[0] if args else None
        nbytes = getattr(obj, "nbytes", None)
        if nbytes is None:
            nbytes = getattr(result, "nbytes", 0) or 0
        dtype = getattr(obj, "dtype", None) or getattr(result, "dtype", "")
        # Compressed ops leave the bytes that actually crossed the wire
        # on the group object (payload + scales); None means wire ==
        # logical and the ledger keeps a 1.0 compression ratio.
        g = GroupManager.get_group(group)
        wire = getattr(g, "_last_wire", None) if g is not None else None
        comms.record_op(group, op_name, int(nbytes), _dtype_str(dtype), dur,
                        world_size=get_collective_group_size(group),
                        wire_bytes=wire)
        if perf.ENABLED:
            perf.observe("collective.op", dur * 1e3)
        return result
    return wrapper


@_collective_wait
def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _group(group_name).allreduce(tensor, op)


@_collective_wait
def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    return _group(group_name).reduce(tensor, dst_rank, op)


@_collective_wait
def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(tensor, src_rank)


@_collective_wait
def allgather(tensor, group_name: str = "default"):
    return _group(group_name).allgather(tensor)


@_collective_wait
def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    return _group(group_name).reducescatter(tensor, op)


@_collective_wait
def send(tensor, dst_rank: int, group_name: str = "default"):
    return _group(group_name).send(tensor, dst_rank)


@_collective_wait
def recv(src_rank: int, group_name: str = "default"):
    return _group(group_name).recv(src_rank)


@_collective_wait
def barrier(group_name: str = "default"):
    return _group(group_name).barrier()


def synchronize(group_name: str = "default"):
    """Block until pending device work completes (the reference syncs CUDA
    streams, ``collective.py:655``; XLA's analogue is draining dispatch)."""
    try:
        import jax
        jax.effects_barrier()
    except Exception:  # raylint: allow(swallow) capability probe: no jax backend
        pass
