"""Cross-process XLA collective group: ranks are daemon processes.

This is the NCCL-communicator replacement for groups whose ranks live in
DIFFERENT OS processes (on hardware: different TPU hosts). The reference
builds per-process NCCL communicators from a rendezvous'd NCCLUniqueID
(``python/ray/util/collective/collective_group/nccl_collective_group.py:127``);
the TPU-native equivalent joins the JAX multi-controller runtime through
the state-service KV (``collective/tensor_plane.py``) and then expresses
every group op as ONE jitted program over a mesh of one lead device per
process — XLA lowers the ``psum``/``all_gather``/``psum_scatter`` onto
ICI/DCN (Gloo on CPU test clusters).

Multi-controller contract: every rank (process) must invoke the same op in
the same order — true of collectives by definition. ``send``/``recv`` are
point-to-point and therefore CANNOT ride a compiled program only two
processes run; they transit the BULK P2P LANE: a direct daemon-to-daemon
``P2P_DATA`` frame whose tensor bytes ride the RPC raw lane
(gather-write out, recv_into in — zero protobuf copies), delivered into
the receiver's p2p mailbox. Ranks publish their RPC address at group
init; when a peer's address is unknown (in-process test planes) the
state-KV path remains as the small-tensor fallback.
"""

from __future__ import annotations
import logging

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.collective.types import ReduceOp

logger = logging.getLogger("ray_tpu")

P2P_NS = b"tplane-p2p"
COMMS_NS = b"tplane-comms"
QUANT_NS = b"tplane-quant"


def _np_dtype(name: str):
    """np.dtype by name, including the ml_dtypes family (bfloat16 etc.)
    that plain numpy only knows once ml_dtypes is imported."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))

_REDUCE = {
    ReduceOp.SUM: lambda a: jnp.sum(a, axis=0),
    ReduceOp.PRODUCT: lambda a: jnp.prod(a, axis=0),
    ReduceOp.MAX: lambda a: jnp.max(a, axis=0),
    ReduceOp.MIN: lambda a: jnp.min(a, axis=0),
}


class XLAProcessGroup:
    """Rank-per-process collective group over the active tensor plane."""

    def __init__(self, world_size: int, rank: int, group_name: str,
                 num_cpu_devices: Optional[int] = None, epoch: int = 0,
                 runtime=None, config=None):
        from ray_tpu.collective.tensor_plane import init_tensor_plane
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self.config = config
        #: wire bytes of the last op when compressed (None = wire ==
        #: logical); read back by the collective API's ledger seam
        self._last_wire = None
        self._q_seq = 0  # quantized-exchange sequence (uniform across ranks)
        init_tensor_plane(group_name, world_size, rank, epoch=epoch,
                          num_cpu_devices=num_cpu_devices, runtime=runtime)
        by_proc: Dict[int, Any] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) != world_size:
            raise RuntimeError(
                f"tensor plane has {len(by_proc)} processes, group wants "
                f"{world_size}")
        self._leads = [by_proc[i] for i in sorted(by_proc)]
        self._local_lead = by_proc[jax.process_index()]
        self.mesh = Mesh(np.array(self._leads), ("p",))
        self._p2p_seq: Dict[tuple, int] = {}
        # Collective sequence number: uniform across ranks because every
        # rank must issue the same ops in the same order (the contract
        # the comms fingerprint check enforces at runtime).
        self._comms_seq = 0
        self._programs: Dict[tuple, Any] = {}  # per-instance, dies with us
        self._publish_p2p_addr()  # bulk p2p reachability (best-effort)

    # -- plumbing -------------------------------------------------------------

    def _stacked(self, tensor):
        """The group-wide (world, *shape) array: this process contributes
        its slice on its lead device; peers contribute theirs."""
        x = jnp.asarray(tensor)
        local = jax.device_put(x[None], self._local_lead)
        sharding = NamedSharding(self.mesh, P("p", *([None] * x.ndim)))
        arr = jax.make_array_from_single_device_arrays(
            (self.world_size,) + x.shape, sharding, [local])
        return arr

    def _program(self, kind: str, op: Optional[ReduceOp], root: int):
        """One jitted program per op kind (jit re-specializes per shape).
        Cached per instance so destroyed groups release their programs."""
        key = (kind, op, root)
        fn = self._programs.get(key)
        if fn is not None:
            return fn
        replicated = NamedSharding(self.mesh, P())
        scattered = NamedSharding(self.mesh, P("p"))
        if kind in ("allreduce", "reduce"):
            fn = jax.jit(_REDUCE[op], out_shardings=replicated)
        elif kind == "broadcast":
            fn = jax.jit(lambda a: a[root], out_shardings=replicated)
        elif kind == "allgather":
            fn = jax.jit(lambda a: a, out_shardings=replicated)
        elif kind == "reducescatter":
            # Each rank contributed (world, chunk...); reduce across ranks
            # then keep the rank'th chunk sharded back onto the lead mesh.
            fn = jax.jit(lambda a: _REDUCE[op](a), out_shardings=scattered)
        else:
            raise ValueError(kind)
        # First call per shape traces+compiles: attribute it to the
        # goodput ledger's ``compile`` category, not collective_wait.
        from ray_tpu.observability import goodput
        fn = goodput.instrument_jit(fn, name=f"collective.{kind}")
        self._programs[key] = fn
        return fn

    @staticmethod
    def _local_value(arr):
        return jnp.asarray(arr.addressable_data(0))

    # -- comms plane (fingerprint exchange + arrival skew over the KV) --------

    def _comms_pre(self, op: str, x,
                   qmeta: tuple = ("none", 0)) -> Optional[tuple]:
        """Publish this rank's (op, shape, dtype) fingerprint + arrival
        stamp for the next collective and cross-check rank 0's before
        launching.  A divergent rank raises CollectiveDivergenceError
        with both fingerprints *pre-launch* — the cross-process face of
        the ``_Rendezvous`` check, where the alternative is the whole
        group hanging inside the runtime.  Waiting for rank 0's key adds
        no critical-path time: the key lands before rank 0 enters the
        very collective we are about to block on anyway."""
        from ray_tpu.observability import comms
        seq = self._comms_seq
        self._comms_seq += 1
        if not comms.ENABLED:
            return None
        import json
        from ray_tpu._private import clocksync
        fp = comms.fingerprint(op, x.shape, x.dtype,
                               scheme=qmeta[0], block=qmeta[1])
        ctx = (seq, time.monotonic())
        try:
            kv = self._kv()
        except RuntimeError:
            return ctx  # no state service: phase timings only
        base = f"{self.group_name}/fp/{seq}"
        # Stamps ride the server timebase so skew compares across hosts.
        rec = json.dumps({"fp": [fp[0], list(fp[1]), fp[2], fp[3], fp[4]],
                          "t": clocksync.to_server_s(time.time())})
        try:
            kv.kv_put(f"{base}/{self.rank}".encode(), rec.encode(),
                      overwrite=True, namespace=COMMS_NS)
        except Exception as e:
            logger.debug("comms fingerprint publish failed: %s", e)
            return ctx
        if self.rank != 0:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                try:
                    raw = kv.kv_get(f"{base}/0".encode(),
                                    namespace=COMMS_NS)
                except Exception:  # raylint: allow(swallow) telemetry degrades, the collective itself must not
                    return ctx
                if raw is not None:
                    other = json.loads(raw.decode())["fp"]
                    # pre-compression peers publish 3 fields; treat the
                    # missing scheme/block as uncompressed
                    theirs = (other[0], tuple(other[1]), other[2],
                              other[3] if len(other) > 3 else "none",
                              int(other[4]) if len(other) > 4 else 0)
                    comms.check_fingerprints({0: theirs, self.rank: fp},
                                             group=self.group_name, seq=seq)
                    break
                # raylint: allow(bare-retry) deadline-bounded KV poll for a peer's key, not a failure retry: backoff would delay the pre-launch divergence check
                time.sleep(0.005)
        return ctx

    def _comms_post(self, ctx: Optional[tuple]) -> None:
        """Launch-phase timing, plus (rank 0) arrival-skew collection: op
        completion implies every rank launched, which implies every rank
        published its stamp — so all keys are present to read, convert
        to skew-after-first-arrival, record, and delete."""
        if ctx is None:
            return
        from ray_tpu.observability import comms, perf
        if not comms.ENABLED:
            return
        seq, t_launch = ctx
        if perf.ENABLED:
            perf.observe("collective.launch",
                         (time.monotonic() - t_launch) * 1e3)
        if self.rank != 0:
            return
        import json
        try:
            kv = self._kv()
        except RuntimeError:
            return
        base = f"{self.group_name}/fp/{seq}"
        stamps: Dict[int, float] = {}
        try:
            for r in range(self.world_size):
                key = f"{base}/{r}".encode()
                raw = kv.kv_get(key, namespace=COMMS_NS)
                if raw is not None:
                    stamps[r] = float(json.loads(raw.decode())["t"])
                kv.kv_del(key, namespace=COMMS_NS)
        except Exception as e:
            logger.debug("comms stamp collect failed: %s", e)
            return
        if len(stamps) == self.world_size:
            first = min(stamps.values())
            comms.record_arrivals(self.group_name,
                                  {r: t - first for r, t in stamps.items()},
                                  self.world_size)

    # -- quantized inter-host exchange (the DCN/TCP seam) ---------------------

    def _quant_active(self, arr) -> bool:
        from ray_tpu.collective import quantization
        return quantization.active(self.config, arr)

    def _quantized_reduce(self, arr: np.ndarray, op: ReduceOp,
                          kind: str) -> np.ndarray:
        """Full reduction over the KV/TCP rendezvous with *quantized*
        payloads — the inter-host hop of the hierarchy. Each process has
        already reduced across its local devices at full precision inside
        the jitted intra-host programs (the ICI hop); what crosses hosts
        here is the block-quantized partial plus per-block scales, and the
        accumulate happens at f32 after dequantization.

        Ranks publish ``{group}/q/{seq}/{rank}`` and collect all peers;
        a rank's ``seq-1`` key is deleted only after it has collected
        every peer's ``seq`` key (everyone publishing seq means everyone
        finished seq-1, so the old generation is safe to drop)."""
        import pickle
        from ray_tpu.collective import quantization
        from ray_tpu.collective.collective_group.cpu_group import \
            _reduce_np_for
        q = quantization.quantize(arr, self.config, group=self.group_name,
                                  op=kind, rank=self.rank)
        self._last_wire = q.wire_bytes
        kv = self._kv()
        seq = self._q_seq
        self._q_seq += 1
        base = f"{self.group_name}/q/{seq}"
        kv.kv_put(f"{base}/{self.rank}".encode(), pickle.dumps(q),
                  overwrite=True, namespace=QUANT_NS)
        payloads: Dict[int, Any] = {self.rank: q}
        deadline = time.monotonic() + 120.0
        while len(payloads) < self.world_size:
            for r in range(self.world_size):
                if r in payloads:
                    continue
                raw = kv.kv_get(f"{base}/{r}".encode(), namespace=QUANT_NS)
                if raw is not None:
                    payloads[r] = pickle.loads(raw)
            if len(payloads) < self.world_size:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"quantized {kind} rendezvous timed out at rank "
                        f"{self.rank} ({len(payloads)}/{self.world_size} "
                        f"payloads)")
                # raylint: allow(bare-retry) deadline-bounded KV poll for peer payloads, not a failure retry
                time.sleep(0.005)
        if seq > 0:
            try:
                kv.kv_del(f"{self.group_name}/q/{seq - 1}/{self.rank}"
                          .encode(), namespace=QUANT_NS)
            except Exception as e:
                logger.debug("quantized payload cleanup failed: %s", e)
        return quantization.reduce_quantized(
            [payloads[r] for r in range(self.world_size)],
            _reduce_np_for(op))

    # -- ops (every process must call, same order) ---------------------------

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        x = jnp.asarray(tensor)
        if self._quant_active(x):
            from ray_tpu.collective import quantization
            meta = quantization.qmeta(self.config, x)
            ctx = self._comms_pre(f"allreduce:{op}", x, qmeta=meta)
            val = jnp.asarray(
                self._quantized_reduce(np.asarray(x), op, "allreduce"))
            self._comms_post(ctx)
            return val
        ctx = self._comms_pre(f"allreduce:{op}", x)
        out = self._program("allreduce", op, 0)(self._stacked(x))
        val = self._local_value(out)
        self._comms_post(ctx)
        return val

    def reduce(self, tensor, root_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        x = jnp.asarray(tensor)
        ctx = self._comms_pre(f"reduce:{op}:{root_rank}", x)
        out = self._local_value(
            self._program("reduce", op, 0)(self._stacked(x)))
        self._comms_post(ctx)
        return out if self.rank == root_rank else x

    def broadcast(self, tensor, root_rank: int = 0):
        self._last_wire = None
        x = jnp.asarray(tensor)
        ctx = self._comms_pre(f"broadcast:{root_rank}", x)
        out = self._program("broadcast", None, root_rank)(self._stacked(x))
        val = self._local_value(out)
        self._comms_post(ctx)
        return val

    def allgather(self, tensor):
        self._last_wire = None
        x = jnp.asarray(tensor)
        ctx = self._comms_pre("allgather", x)
        out = self._program("allgather", None, 0)(self._stacked(x))
        val = self._local_value(out)
        self._comms_post(ctx)
        return val

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        """Each rank contributes a tensor whose leading dim divides into
        ``world_size`` chunks; rank r receives chunk r of the reduction
        (same contract as the in-process groups, test_collective.py:78)."""
        self._last_wire = None
        x = jnp.asarray(tensor)
        if x.shape[0] % self.world_size:
            raise ValueError(
                f"reducescatter leading dim {x.shape[0]} not divisible by "
                f"world size {self.world_size}")
        chunk = x.shape[0] // self.world_size
        if self._quant_active(x):
            from ray_tpu.collective import quantization
            meta = quantization.qmeta(self.config, x)
            ctx = self._comms_pre(f"reducescatter:{op}", x, qmeta=meta)
            red = self._quantized_reduce(np.asarray(x), op, "reducescatter")
            val = jnp.asarray(
                red[self.rank * chunk:(self.rank + 1) * chunk])
            self._comms_post(ctx)
            return val
        ctx = self._comms_pre(f"reducescatter:{op}", x)
        chunks = x.reshape((self.world_size, chunk) + x.shape[1:])
        arr = self._stacked(chunks)  # (world, world, chunk...)
        out = self._program("reducescatter", op, 0)(arr)
        val = self._local_value(out)[0]
        self._comms_post(ctx)
        return val

    def barrier(self):
        self.allreduce(jnp.zeros((), jnp.int32))

    # -- p2p over the state KV (control-plane; small tensors) ----------------

    def _kv(self):
        from ray_tpu._private import worker as _worker
        runtime = _worker.try_global_runtime()
        state = getattr(runtime, "state", None)
        if state is None:
            raise RuntimeError("p2p needs the cluster state service")
        return state

    def _runtime(self):
        from ray_tpu._private import worker as _worker
        return _worker.try_global_runtime()

    def _publish_p2p_addr(self):
        """Make this rank reachable for bulk p2p (idempotent)."""
        rt = self._runtime()
        addr = getattr(rt, "address", None)
        if addr:
            try:
                self._kv().kv_put(
                    f"{self.group_name}/addr/{self.rank}".encode(),
                    addr.encode(), overwrite=True, namespace=P2P_NS)
            except Exception as e:
                logger.debug("p2p address publish failed: %s", e)

    def _peer_addr(self, rank: int) -> Optional[str]:
        try:
            raw = self._kv().kv_get(
                f"{self.group_name}/addr/{rank}".encode(),
                namespace=P2P_NS)
            return raw.decode() if raw else None
        except Exception as e:
            logger.debug("peer address lookup failed: %s", e)
            return None

    def send(self, tensor, dst_rank: int):
        self._last_wire = None
        seq = self._p2p_seq.get(("s", dst_rank), 0)
        self._p2p_seq[("s", dst_rank)] = seq + 1
        arr = np.ascontiguousarray(np.asarray(tensor))
        rt = self._runtime()
        addr = self._peer_addr(dst_rank)
        if addr and getattr(rt, "pool", None) is not None:
            # Bulk lane: metadata in the envelope, bytes gather-written
            # from the array's buffer — no pickle, no KV round-trips.
            # byte-view first: bf16 & friends (ml_dtypes) have no buffer
            # protocol, and bf16 is the dominant dtype on this hardware.
            from ray_tpu.protocol import pb
            msg = pb.P2PDataMsg(
                group=self.group_name, src_rank=self.rank,
                dst_rank=dst_rank, p2p_seq=seq, dtype=str(arr.dtype),
                shape=list(arr.shape))
            rt.pool.get(addr).call(pb.P2P_DATA, msg.SerializeToString(),
                                   timeout=120,
                                   raw=arr.view(np.uint8).reshape(-1))
            return
        # Fallback (no RPC address: in-process planes): state-KV path.
        import pickle
        key = f"{self.group_name}/{self.rank}>{dst_rank}/{seq}".encode()
        self._kv().kv_put(key, pickle.dumps(arr), overwrite=True,
                          namespace=P2P_NS)

    def recv(self, src_rank: int, timeout_s: float = 30.0):
        self._last_wire = None
        import pickle
        seq = self._p2p_seq.get(("r", src_rank), 0)
        self._p2p_seq[("r", src_rank)] = seq + 1
        rt = self._runtime()
        if hasattr(rt, "p2p_wait"):
            box_key = (self.group_name, src_rank, self.rank, seq)
            kv_key = (f"{self.group_name}/{src_rank}>{self.rank}/{seq}"
                      .encode())
            deadline = time.monotonic() + timeout_s
            while True:
                # Primarily wait on the mailbox (event-driven); probe the
                # KV fallback only at a coarse 1s interval — the sender
                # uses the KV path only when OUR address is unpublished,
                # and a tight kv_get loop would hammer the control plane
                # with no-op RPCs (one per 50ms per blocked rank).
                try:
                    dtype, shape, data = rt.p2p_wait(box_key,
                                                     timeout_s=1.0)
                    return jnp.asarray(
                        np.frombuffer(data, dtype=_np_dtype(dtype))
                        .reshape(shape))
                except TimeoutError:
                    pass
                raw = self._kv().kv_get(kv_key, namespace=P2P_NS)
                if raw is not None:
                    self._kv().kv_del(kv_key, namespace=P2P_NS)
                    return jnp.asarray(pickle.loads(raw))
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"recv from rank {src_rank} timed out")
        key = f"{self.group_name}/{src_rank}>{self.rank}/{seq}".encode()
        kv = self._kv()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            raw = kv.kv_get(key, namespace=P2P_NS)
            if raw is not None:
                kv.kv_del(key, namespace=P2P_NS)
                return jnp.asarray(pickle.loads(raw))
            time.sleep(0.005)
        raise TimeoutError(f"recv from rank {src_rank} timed out")

    def destroy(self):
        # The tensor plane outlives individual groups (other groups and the
        # trainer share it); it is torn down by shutdown_tensor_plane() or
        # superseded when a new epoch re-forms.
        pass
