"""XLA collective group: MPI-style group ops that compile onto the TPU mesh.

This replaces the reference's NCCL group
(``python/ray/util/collective/collective_group/nccl_collective_group.py:127``)
the TPU way: instead of cupy NCCL communicators + CUDA stream pools, a group
binds its ranks to the devices of a ``jax.sharding.Mesh`` and every op is a
jitted ``shard_map`` program whose collective (``jax.lax.psum`` /
``all_gather`` / ``psum_scatter`` / ``ppermute``) XLA lowers onto ICI.
Rendezvous is an in-process barrier (the reference needs a named-actor
NCCLUniqueID store, ``nccl_collective_group.py:54-95``; host-granular
runtimes don't).

Ranks are callers (actor/task threads). Each rank deposits its tensor at the
rendezvous; the last arrival assembles a global sharded array
(``jax.make_array_from_single_device_arrays``) and launches ONE compiled
program for the whole group; every rank then reads its addressable shard.
When the host has fewer devices than ranks, ranks fold onto devices
round-robin and the op runs as a single-device reduction (still one fused
XLA program).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from ray_tpu._private.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.collective import quantization
from ray_tpu.collective.types import ReduceOp
from ray_tpu.observability import comms, perf

_REDUCE_LAX = {
    ReduceOp.SUM: lambda x, axis: jax.lax.psum(x, axis),
    ReduceOp.MAX: lambda x, axis: jax.lax.pmax(x, axis),
    ReduceOp.MIN: lambda x, axis: jax.lax.pmin(x, axis),
}

_REDUCE_NP = {
    ReduceOp.SUM: jnp.sum,
    ReduceOp.PRODUCT: jnp.prod,
    ReduceOp.MIN: jnp.min,
    ReduceOp.MAX: jnp.max,
}


class _Rendezvous:
    """All ranks deposit; last arrival runs ``compute`` once; all collect.

    When the comms plane is on (and the rendezvous belongs to a named
    group — p2p pair rendezvous pass ``label=None`` and stay dark), each
    rank stamps its arrival and deposits its collective fingerprint; the
    last arrival checks the fingerprints (divergence raises into the
    shared outcome, so every rank sees the error instead of computing
    with the wrong op) and records the per-rank arrival-skew
    distribution that lets the doctor name a laggard rank.
    """

    def __init__(self, world_size: int, label: Optional[str] = "default"):
        self.world_size = world_size
        self.label = label
        self.lock = threading.Lock()
        self.slots: Dict[int, Any] = {}
        self.stamps: Dict[int, float] = {}
        self.fps: Dict[int, tuple] = {}
        # Per-generation outcomes so one failed collective doesn't poison the
        # next: outcome[gen] = (result, error). Old generations are pruned.
        self.outcomes: Dict[int, tuple] = {}
        self.generation = 0
        self.cv = threading.Condition(self.lock)

    def run(self, rank: int, value: Any, compute: Callable[[Dict[int, Any]], Any],
            timeout: float = 30.0, fingerprint: Optional[tuple] = None) -> Any:
        # Stamp before taking the lock so lock contention doesn't
        # masquerade as rank arrival skew.
        observed = comms.ENABLED and self.label is not None
        t_arrive = time.monotonic() if observed else 0.0
        stamps = launch_ms = None
        with self.cv:
            gen = self.generation
            self.slots[rank] = value
            if observed:
                self.stamps[rank] = t_arrive
                if fingerprint is not None:
                    self.fps[rank] = fingerprint
            if len(self.slots) == self.world_size:
                stamps, fps = self.stamps, self.fps
                self.stamps, self.fps = {}, {}
                try:
                    if len(fps) == self.world_size:
                        comms.check_fingerprints(fps, group=self.label,
                                                 seq=gen)
                    t_launch = time.monotonic() if observed else 0.0
                    result = compute(dict(self.slots))
                    if observed:
                        launch_ms = (time.monotonic() - t_launch) * 1e3
                    self.outcomes[gen] = (result, None)
                except BaseException as e:  # noqa: BLE001
                    self.outcomes[gen] = (None, e)
                self.slots.clear()
                self.generation += 1
                for old in [g for g in self.outcomes if g < gen - 2]:
                    del self.outcomes[old]
                self.cv.notify_all()
            else:
                if not self.cv.wait_for(lambda: self.generation > gen,
                                        timeout=timeout):
                    self.slots.pop(rank, None)
                    self.stamps.pop(rank, None)
                    self.fps.pop(rank, None)
                    raise TimeoutError(
                        f"collective rendezvous timed out at rank {rank} "
                        f"({len(self.slots)}/{self.world_size} arrived)")
            result, error = self.outcomes[gen]
        # Ledger writes happen OUTSIDE the rendezvous critical section:
        # they take the comms/perf locks, and every microsecond spent
        # holding the condition variable extends the window in which the
        # other ranks stay parked (and, under the GIL, stretches the
        # whole group's op latency).
        if observed and stamps is not None and len(stamps) == self.world_size:
            first = min(stamps.values())
            comms.record_arrivals(
                self.label, {r: t - first for r, t in stamps.items()},
                self.world_size)
        if error is not None:
            raise error
        if observed and perf.ENABLED:
            if launch_ms is not None:
                perf.observe("collective.launch", launch_ms)
            perf.observe("collective.collect",
                         (time.monotonic() - t_arrive) * 1e3)
        return result


class XLAGroup:
    def __init__(self, world_size: int, rank: int, group_name: str,
                 shared: "XLAGroupShared", config=None):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self.config = config
        self._shared = shared
        #: wire bytes of the last op when compressed (None = wire ==
        #: logical); read back by the collective API's ledger seam
        self._last_wire = None

    # -- ops ------------------------------------------------------------------

    def _hierarchical(self) -> bool:
        cfg = self.config
        return (cfg is not None and cfg.ranks_per_host > 1
                and self.world_size % cfg.ranks_per_host == 0
                and self.world_size != cfg.ranks_per_host)

    def _compressed(self, arr, kind: str, op: ReduceOp):
        """Quantized allreduce/reducescatter: the payload is compressed at
        the host seam (the compression tier models the expensive DCN hop;
        intra-host ICI programs stay full precision)."""
        cfg = self.config
        meta = quantization.qmeta(cfg, arr)
        if kind == "allreduce" and self._hierarchical():
            res = self._shared.collective(
                self.rank, arr, (kind, op, "hier", cfg.ranks_per_host),
                qmeta=meta, qconfig=cfg)
            self._last_wire = res.get("wire")
            return res[self.rank]
        try:
            q = quantization.quantize(arr, cfg, group=self.group_name,
                                      op=kind, rank=self.rank)
        except Exception as e:
            # Still arrive at the rendezvous: the fault sentinel makes the
            # shared compute raise this error for EVERY rank (fail loudly)
            # instead of stranding the peers until their timeout.
            self._shared.collective(
                self.rank,
                quantization.QuantFault(e, tuple(arr.shape),
                                        np.dtype(arr.dtype)),
                (kind, op), qmeta=meta, qconfig=cfg)
            raise
        self._last_wire = q.wire_bytes
        return self._shared.collective(self.rank, q, (kind, op),
                                       qmeta=meta, qconfig=cfg)[self.rank]

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        arr = np.asarray(tensor)
        if quantization.active(self.config, arr):
            return self._compressed(arr, "allreduce", op)
        results = self._shared.collective(self.rank, tensor, ("allreduce", op))
        return results[self.rank]

    def reduce(self, tensor, root_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        results = self._shared.collective(self.rank, tensor, ("reduce", op, root_rank))
        return results[self.rank]

    def broadcast(self, tensor, root_rank: int = 0):
        self._last_wire = None
        results = self._shared.collective(self.rank, tensor, ("broadcast", root_rank))
        return results[self.rank]

    def allgather(self, tensor):
        self._last_wire = None
        results = self._shared.collective(self.rank, tensor, ("allgather",))
        return results[self.rank]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        arr = np.asarray(tensor)
        if quantization.active(self.config, arr):
            return self._compressed(arr, "reducescatter", op)
        results = self._shared.collective(self.rank, tensor, ("reducescatter", op))
        return results[self.rank]

    def barrier(self):
        self._last_wire = None
        self._shared.collective(self.rank, jnp.zeros((), jnp.int32), ("barrier",))

    def send(self, tensor, dst_rank: int):
        self._last_wire = None
        self._shared.p2p_send(self.rank, dst_rank, tensor)

    def recv(self, src_rank: int):
        self._last_wire = None
        return self._shared.p2p_recv(self.rank, src_rank)

    def destroy(self):
        pass


class XLAGroupShared:
    """State shared by all ranks of one group in this process."""

    def __init__(self, world_size: int, devices: Optional[List] = None,
                 label: str = "default"):
        self.world_size = world_size
        self.label = label
        devs = devices if devices is not None else jax.devices()
        # Fold ranks onto devices round-robin when ranks > devices.
        self.rank_devices = [devs[i % len(devs)] for i in range(world_size)]
        self.distinct = len(set(d.id for d in self.rank_devices)) == world_size
        if self.distinct:
            self.mesh = Mesh(np.array(self.rank_devices), ("ranks",))
        else:
            self.mesh = None
        self._rdv = _Rendezvous(world_size, label=label)
        self._p2p: Dict[tuple, _Rendezvous] = {}
        self._p2p_lock = threading.Lock()
        self._compiled: Dict[tuple, Callable] = {}

    # one fused program per (op kind, shape, dtype)
    def _program(self, key: tuple, builder: Callable) -> Callable:
        fn = self._compiled.get(key)
        if fn is None:
            fn = builder()
            self._compiled[key] = fn
        return fn

    def collective(self, rank: int, tensor, op_desc: tuple,
                   qmeta: tuple = ("none", 0),
                   qconfig=None) -> Dict[Any, Any]:
        if isinstance(tensor, (quantization.Quantized,
                               quantization.QuantFault)):
            shape, dtype = tensor.shape, tensor.dtype
        else:
            tensor = jnp.asarray(tensor)
            shape, dtype = tuple(tensor.shape), tensor.dtype
        # Raw-tuple fingerprint: (op_desc, shape, dtype) compares by
        # value; stringifying enum/dtype per op costs more than the rest
        # of the ledger combined, so it only happens in the divergence
        # error message (the cross-process path, which must publish
        # JSON-safe fingerprints, uses comms.fingerprint instead). The
        # trailing (scheme, block_elems) pair makes mixed-compression
        # ranks diverge loudly instead of mixing payload types.
        fp = ((op_desc, shape, dtype) + tuple(qmeta)) \
            if comms.ENABLED else None

        def compute(slots: Dict[int, Any]) -> Dict[Any, Any]:
            for v in slots.values():
                if isinstance(v, quantization.QuantFault):
                    raise v.error
            if "hier" in op_desc or isinstance(
                    slots[0], quantization.Quantized):
                return self._run_quantized_op(slots, op_desc, qconfig)
            return self._run_group_op(slots, op_desc)

        return self._rdv.run(rank, tensor, compute, fingerprint=fp)

    def _run_quantized_op(self, slots: Dict[int, Any], op_desc: tuple,
                          qconfig) -> Dict[Any, Any]:
        """Compressed allreduce/reducescatter, staged on the host: the
        dequant-fused reduction happens at f32 in the quant kernels (the
        compression tier targets the expensive inter-host hop, so the
        intra-host ICI mesh programs are deliberately not part of it)."""
        kind = op_desc[0]
        op = op_desc[1]
        reduce_np = (None if op == ReduceOp.SUM
                     else (lambda xs: np.asarray(_REDUCE_NP[op](
                         jnp.asarray(xs), axis=0))))
        vals = [slots[r] for r in range(self.world_size)]
        if "hier" in op_desc:
            red, wire = quantization.hierarchical_allreduce(
                vals, qconfig, reduce_np,
                group=self.label or "default", op_name=kind)
            out: Dict[Any, Any] = {r: jnp.asarray(red)
                                   for r in range(self.world_size)}
            out["wire"] = wire
            return out
        red = jnp.asarray(quantization.reduce_quantized(vals, reduce_np))
        if kind == "allreduce":
            return {r: red for r in range(self.world_size)}
        chunks = jnp.split(red, self.world_size, axis=0)
        return {r: chunks[r] for r in range(self.world_size)}

    # -- the single fused program for the whole group -------------------------

    def _run_group_op(self, slots: Dict[int, Any], op_desc: tuple) -> Dict[int, Any]:
        kind = op_desc[0]
        xs = [slots[r] for r in range(self.world_size)]
        if kind == "barrier":
            return {r: None for r in range(self.world_size)}
        if self.distinct and self.mesh is not None and kind in (
                "allreduce", "reducescatter", "allgather", "reduce",
                "broadcast"):
            return self._run_mesh_op(xs, op_desc)
        if kind == "broadcast":
            # folded ranks share devices: every rank reads the same buffer
            # (the distinct-devices case routed into _run_mesh_op above)
            src = xs[op_desc[1]]
            return {r: src for r in range(self.world_size)}
        return self._run_host_op(xs, op_desc)

    def _run_mesh_op(self, xs: List[Any], op_desc: tuple) -> Dict[int, Any]:
        """One shard_map program over the group mesh; collectives ride ICI."""
        kind = op_desc[0]
        shape, dtype = xs[0].shape, xs[0].dtype
        key = (kind,) + tuple(op_desc[1:]) + (shape, str(dtype))

        def builder():
            axis = "ranks"
            if kind == "allreduce":
                op = op_desc[1]
                if op == ReduceOp.PRODUCT:
                    body = lambda x: jnp.prod(  # noqa: E731
                        jax.lax.all_gather(x, axis), axis=0)
                else:
                    body = lambda x: _REDUCE_LAX[op](x, axis)  # noqa: E731
                out_spec = P("ranks")
            elif kind == "reduce":
                op = op_desc[1]
                body = lambda x: _REDUCE_LAX[op](x, axis)  # noqa: E731
                out_spec = P("ranks")
            elif kind == "allgather":
                # Block is [1, *shape]; gather the squeezed tensor so every
                # rank's output block is the stacked [world, *shape].
                body = lambda x: jax.lax.all_gather(x[0], axis)  # noqa: E731
                out_spec = P("ranks")
            elif kind == "reducescatter":
                op = op_desc[1]
                # Scatter over the *user* tensor's dim 0 (block dim 1):
                # squeeze the rank dim first; each rank's output block is its
                # [shape0/world, ...] chunk of the summed tensor.
                body = lambda x: jax.lax.psum_scatter(  # noqa: E731
                    x[0], axis, scatter_dimension=0, tiled=True)
                out_spec = P("ranks")
            elif kind == "broadcast":
                # one compiled O(N)-per-device fan-out from root over ICI:
                # psum of the root-masked tensor (all_gather would move
                # and transiently materialize world_size x the tensor;
                # ppermute cannot express one-to-many)
                root = op_desc[1]
                # astype: psum converts bool inputs to integers — the
                # broadcast result must keep the input dtype
                body = lambda x: jax.lax.psum(  # noqa: E731
                    jnp.where(jax.lax.axis_index(axis) == root, x,
                              jnp.zeros_like(x)), axis).astype(x.dtype)
                out_spec = P("ranks")
            else:
                raise ValueError(kind)
            fn = shard_map(body, mesh=self.mesh, in_specs=P("ranks"),
                           out_specs=out_spec, check_vma=False)
            # first-trace time is compile, not collective_wait
            from ray_tpu.observability import goodput
            return goodput.instrument_jit(jax.jit(fn),
                                          name=f"collective.{kind}")

        fn = self._program(key, builder)
        stacked_shape = (self.world_size,) + tuple(shape)
        sharding = NamedSharding(self.mesh, P("ranks"))
        global_arr = jax.make_array_from_single_device_arrays(
            stacked_shape, sharding,
            [jax.device_put(x[None], d) for x, d in zip(xs, self.rank_devices)])
        out = fn(global_arr)
        shards = {s.device.id: s.data for s in out.addressable_shards}
        # allreduce/reduce/broadcast blocks carry a leading rank dim of 1 to
        # squeeze; allgather blocks are the full stack and reducescatter
        # blocks are the rank's chunk — returned as-is.
        squeeze = kind in ("allreduce", "reduce", "broadcast")
        results = {}
        for r, d in enumerate(self.rank_devices):
            local = shards[d.id]
            results[r] = local[0] if squeeze else local
        if op_desc[0] == "reduce":
            root = op_desc[2]
            # non-roots get their input back (reference reduce semantics:
            # only root receives the reduction)
            results = {r: (results[r] if r == root else xs[r])
                       for r in range(self.world_size)}
        return results

    def _run_host_op(self, xs: List[Any], op_desc: tuple) -> Dict[int, Any]:
        """Ranks folded on one device: a single stacked-reduction program."""
        kind = op_desc[0]
        stacked = jnp.stack(xs)
        if kind == "allreduce":
            red = _REDUCE_NP[op_desc[1]](stacked, axis=0)
            return {r: red for r in range(self.world_size)}
        if kind == "reduce":
            red = _REDUCE_NP[op_desc[1]](stacked, axis=0)
            root = op_desc[2]
            return {r: (red if r == root else xs[r])
                    for r in range(self.world_size)}
        if kind == "allgather":
            return {r: stacked for r in range(self.world_size)}
        if kind == "reducescatter":
            red = _REDUCE_NP[op_desc[1]](stacked, axis=0)
            chunks = jnp.split(red, self.world_size, axis=0)
            return {r: chunks[r] for r in range(self.world_size)}
        raise ValueError(kind)

    # -- point to point -------------------------------------------------------

    def _pair_rdv(self, src: int, dst: int) -> _Rendezvous:
        with self._p2p_lock:
            key = (src, dst)
            rdv = self._p2p.get(key)
            if rdv is None:
                # label=None: pair rendezvous carry asymmetric values by
                # design, so no fingerprint check and no skew attribution.
                rdv = _Rendezvous(2, label=None)
                self._p2p[key] = rdv
            return rdv

    def _p2p_transfer(self, src: int, dst: int, tensor):
        """Move ``tensor`` from src's device to dst's device.

        Distinct devices: ONE compiled ``ppermute`` over the (src, dst)
        pair mesh — the transfer rides ICI like any other collective, not
        a host-mediated ``device_put`` copy. Folded ranks: same buffer."""
        src_dev = self.rank_devices[src]
        dst_dev = self.rank_devices[dst]
        if not self.distinct or src_dev.id == dst_dev.id:
            return tensor
        shape, dtype = tensor.shape, tensor.dtype
        key = ("p2p", src_dev.id, dst_dev.id, tuple(shape), str(dtype))

        def builder():
            mesh = Mesh(np.array([src_dev, dst_dev]), ("pair",))
            fn = jax.jit(shard_map(
                lambda x: jax.lax.ppermute(x, "pair", [(0, 1)]),
                mesh=mesh, in_specs=P("pair"), out_specs=P("pair"),
                check_vma=False))
            return fn, mesh

        fn, mesh = self._program(key, builder)
        stacked = jax.make_array_from_single_device_arrays(
            (2,) + tuple(shape), NamedSharding(mesh, P("pair")),
            [jax.device_put(tensor[None], src_dev),
             jax.device_put(jnp.zeros((1,) + tuple(shape), dtype),
                            dst_dev)])
        out = fn(stacked)
        for s in out.addressable_shards:
            if s.device.id == dst_dev.id:
                return s.data[0]
        return jax.device_put(tensor, dst_dev)  # unreachable fallback

    def p2p_send(self, rank: int, dst_rank: int, tensor):
        rdv = self._pair_rdv(rank, dst_rank)

        def compute(slots):
            return self._p2p_transfer(rank, dst_rank, slots[rank])

        rdv.run(rank, jnp.asarray(tensor), compute)

    def p2p_recv(self, rank: int, src_rank: int):
        rdv = self._pair_rdv(src_rank, rank)
        return rdv.run(
            rank, None,
            lambda slots: self._p2p_transfer(src_rank, rank,
                                             slots[src_rank]))
