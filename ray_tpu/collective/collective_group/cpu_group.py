"""CPU (numpy) collective group — the GLOO-role backend.

Parity with ``python/ray/util/collective/collective_group/gloo_collective_group.py:184``:
host-tensor collectives for CPU-only actors and tests, sharing the same
rendezvous machinery as the XLA group but computing with numpy.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.collective.collective_group.xla_group import _Rendezvous
from ray_tpu.collective.types import ReduceOp
from ray_tpu.observability import comms

_NP_REDUCE = {
    ReduceOp.SUM: lambda xs: np.sum(xs, axis=0),
    ReduceOp.PRODUCT: lambda xs: np.prod(xs, axis=0),
    ReduceOp.MIN: lambda xs: np.min(xs, axis=0),
    ReduceOp.MAX: lambda xs: np.max(xs, axis=0),
}


class CPUGroupShared:
    def __init__(self, world_size: int, devices: Optional[List] = None,
                 label: str = "default"):
        self.world_size = world_size
        self.label = label
        # Shared rendezvous = same comms instrumentation as the XLA
        # group: arrival stamps, fingerprint check, launch/collect phases.
        self._rdv = _Rendezvous(world_size, label=label)
        self._p2p: Dict[tuple, _Rendezvous] = {}
        import threading
        self._p2p_lock = threading.Lock()

    def collective(self, rank: int, tensor, op_desc: tuple) -> Dict[int, Any]:
        arr = np.asarray(tensor)
        # Raw-tuple fingerprint — see XLAGroupShared.collective: equality
        # is what the divergence check needs, and per-op stringification
        # is the single biggest avoidable ledger cost.
        fp = ((op_desc, tuple(arr.shape), arr.dtype)
              if comms.ENABLED else None)

        def compute(slots):
            kind = op_desc[0]
            xs = np.stack([np.asarray(slots[r]) for r in range(self.world_size)])
            if kind == "barrier":
                return {r: None for r in range(self.world_size)}
            if kind == "broadcast":
                return {r: xs[op_desc[1]] for r in range(self.world_size)}
            if kind == "allreduce":
                red = _NP_REDUCE[op_desc[1]](xs)
                return {r: red for r in range(self.world_size)}
            if kind == "reduce":
                red = _NP_REDUCE[op_desc[1]](xs)
                return {r: (red if r == op_desc[2] else xs[r])
                        for r in range(self.world_size)}
            if kind == "allgather":
                return {r: xs for r in range(self.world_size)}
            if kind == "reducescatter":
                red = _NP_REDUCE[op_desc[1]](xs)
                chunks = np.split(red, self.world_size, axis=0)
                return {r: chunks[r] for r in range(self.world_size)}
            raise ValueError(kind)

        return self._rdv.run(rank, arr, compute, fingerprint=fp)

    def _pair_rdv(self, src: int, dst: int) -> _Rendezvous:
        with self._p2p_lock:
            key = (src, dst)
            if key not in self._p2p:
                # label=None: no fingerprint/skew on asymmetric p2p pairs.
                self._p2p[key] = _Rendezvous(2, label=None)
            return self._p2p[key]

    def p2p_send(self, rank: int, dst_rank: int, tensor):
        rdv = self._pair_rdv(rank, dst_rank)
        rdv.run(rank, np.asarray(tensor), lambda slots: slots[rank])

    def p2p_recv(self, rank: int, src_rank: int):
        rdv = self._pair_rdv(src_rank, rank)
        return rdv.run(rank, None, lambda slots: slots[src_rank])


class CPUGroup:
    def __init__(self, world_size: int, rank: int, group_name: str,
                 shared: CPUGroupShared):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._shared = shared

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._shared.collective(self.rank, tensor, ("allreduce", op))[self.rank]

    def reduce(self, tensor, root_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        return self._shared.collective(self.rank, tensor,
                                       ("reduce", op, root_rank))[self.rank]

    def broadcast(self, tensor, root_rank: int = 0):
        return self._shared.collective(self.rank, tensor,
                                       ("broadcast", root_rank))[self.rank]

    def allgather(self, tensor):
        return self._shared.collective(self.rank, tensor, ("allgather",))[self.rank]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._shared.collective(self.rank, tensor,
                                       ("reducescatter", op))[self.rank]

    def barrier(self):
        self._shared.collective(self.rank, np.zeros(()), ("barrier",))

    def send(self, tensor, dst_rank: int):
        self._shared.p2p_send(self.rank, dst_rank, tensor)

    def recv(self, src_rank: int):
        return self._shared.p2p_recv(self.rank, src_rank)

    def destroy(self):
        pass
