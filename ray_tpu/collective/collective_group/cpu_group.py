"""CPU (numpy) collective group — the GLOO-role backend.

Parity with ``python/ray/util/collective/collective_group/gloo_collective_group.py:184``:
host-tensor collectives for CPU-only actors and tests, sharing the same
rendezvous machinery as the XLA group but computing with numpy.

Compression tier (``CollectiveConfig(compression="q8"|"fp8")``): ranks
quantize their allreduce/reducescatter payloads block-wise before the
deposit; the last arrival widens them back to f32 *inside* the reduction
(``quantization.reduce_quantized``), so accumulation is always full
precision. With ``ranks_per_host`` the allreduce becomes two-level:
intra-host spans reduce at full precision and only the per-host partials
move quantized. The (scheme, block) pair rides every rank's rendezvous
fingerprint — mixed q8/f32 ranks raise
:class:`~ray_tpu.observability.comms.CollectiveDivergenceError` instead
of corrupting the sum with a half-quantized accumulate.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.collective import quantization
from ray_tpu.collective.collective_group.xla_group import _Rendezvous
from ray_tpu.collective.types import ReduceOp
from ray_tpu.observability import comms

_NP_REDUCE = {
    ReduceOp.SUM: lambda xs: np.sum(xs, axis=0),
    ReduceOp.PRODUCT: lambda xs: np.prod(xs, axis=0),
    ReduceOp.MIN: lambda xs: np.min(xs, axis=0),
    ReduceOp.MAX: lambda xs: np.max(xs, axis=0),
}


def _reduce_np_for(op: ReduceOp):
    """SUM takes the fused dequant+accumulate path (None); the rest widen
    each payload before reducing."""
    return None if op == ReduceOp.SUM else _NP_REDUCE[op]


class CPUGroupShared:
    def __init__(self, world_size: int, devices: Optional[List] = None,
                 label: str = "default"):
        self.world_size = world_size
        self.label = label
        # Shared rendezvous = same comms instrumentation as the XLA
        # group: arrival stamps, fingerprint check, launch/collect phases.
        self._rdv = _Rendezvous(world_size, label=label)
        self._p2p: Dict[tuple, _Rendezvous] = {}
        import threading
        self._p2p_lock = threading.Lock()

    def collective(self, rank: int, value, op_desc: tuple,
                   qmeta: tuple = ("none", 0),
                   qconfig=None) -> Dict[Any, Any]:
        if isinstance(value, (quantization.Quantized,
                              quantization.QuantFault)):
            shape, dtype = value.shape, value.dtype
        else:
            value = np.asarray(value)
            shape, dtype = tuple(value.shape), value.dtype
        # Raw-tuple fingerprint — see XLAGroupShared.collective: equality
        # is what the divergence check needs, and per-op stringification
        # is the single biggest avoidable ledger cost. The trailing
        # (scheme, block_elems) pair is the compression identity.
        fp = ((op_desc, shape, dtype) + tuple(qmeta)) \
            if comms.ENABLED else None

        def compute(slots):
            kind = op_desc[0]
            vals = [slots[r] for r in range(self.world_size)]
            for v in vals:
                if isinstance(v, quantization.QuantFault):
                    raise v.error
            if "hier" in op_desc:
                red, wire = quantization.hierarchical_allreduce(
                    vals, qconfig, _reduce_np_for(op_desc[1]),
                    group=self.label or "default", op_name=kind)
                out: Dict[Any, Any] = {r: red
                                       for r in range(self.world_size)}
                out["wire"] = wire
                return out
            if isinstance(vals[0], quantization.Quantized):
                red = quantization.reduce_quantized(
                    vals, _reduce_np_for(op_desc[1]))
                if kind == "allreduce":
                    return {r: red for r in range(self.world_size)}
                chunks = np.split(red, self.world_size, axis=0)
                return {r: chunks[r] for r in range(self.world_size)}
            xs = np.stack([np.asarray(slots[r])
                           for r in range(self.world_size)])
            if kind == "barrier":
                return {r: None for r in range(self.world_size)}
            if kind == "broadcast":
                return {r: xs[op_desc[1]] for r in range(self.world_size)}
            if kind == "allreduce":
                red = _NP_REDUCE[op_desc[1]](xs)
                return {r: red for r in range(self.world_size)}
            if kind == "reduce":
                red = _NP_REDUCE[op_desc[1]](xs)
                return {r: (red if r == op_desc[2] else xs[r])
                        for r in range(self.world_size)}
            if kind == "allgather":
                return {r: xs for r in range(self.world_size)}
            if kind == "reducescatter":
                red = _NP_REDUCE[op_desc[1]](xs)
                chunks = np.split(red, self.world_size, axis=0)
                return {r: chunks[r] for r in range(self.world_size)}
            raise ValueError(kind)

        return self._rdv.run(rank, value, compute, fingerprint=fp)

    def _pair_rdv(self, src: int, dst: int) -> _Rendezvous:
        with self._p2p_lock:
            key = (src, dst)
            if key not in self._p2p:
                # label=None: no fingerprint/skew on asymmetric p2p pairs.
                self._p2p[key] = _Rendezvous(2, label=None)
            return self._p2p[key]

    def p2p_send(self, rank: int, dst_rank: int, tensor):
        rdv = self._pair_rdv(rank, dst_rank)
        rdv.run(rank, np.asarray(tensor), lambda slots: slots[rank])

    def p2p_recv(self, rank: int, src_rank: int):
        rdv = self._pair_rdv(src_rank, rank)
        return rdv.run(rank, None, lambda slots: slots[src_rank])


class CPUGroup:
    def __init__(self, world_size: int, rank: int, group_name: str,
                 shared: CPUGroupShared, config=None):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self.config = config
        self._shared = shared
        #: wire bytes of the last op when compressed (None = wire ==
        #: logical); the collective API seam feeds it to the comms ledger
        self._last_wire = None

    def _hierarchical(self) -> bool:
        cfg = self.config
        return (cfg is not None and cfg.ranks_per_host > 1
                and self.world_size % cfg.ranks_per_host == 0
                and self.world_size != cfg.ranks_per_host)

    def _compressed(self, arr: np.ndarray, kind: str, op: ReduceOp):
        """Quantized allreduce/reducescatter; returns this rank's result."""
        cfg = self.config
        meta = quantization.qmeta(cfg, arr)
        if kind == "allreduce" and self._hierarchical():
            res = self._shared.collective(
                self.rank, arr, (kind, op, "hier", cfg.ranks_per_host),
                qmeta=meta, qconfig=cfg)
            self._last_wire = res.get("wire")
            return res[self.rank]
        try:
            q = quantization.quantize(arr, cfg, group=self.group_name,
                                      op=kind, rank=self.rank)
        except Exception as e:
            # Still arrive at the rendezvous: the fault sentinel makes the
            # shared compute raise this error for EVERY rank (fail loudly)
            # instead of stranding the peers until their timeout.
            self._shared.collective(
                self.rank,
                quantization.QuantFault(e, tuple(arr.shape), arr.dtype),
                (kind, op), qmeta=meta, qconfig=cfg)
            raise
        self._last_wire = q.wire_bytes
        return self._shared.collective(self.rank, q, (kind, op),
                                       qmeta=meta, qconfig=cfg)[self.rank]

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        arr = np.asarray(tensor)
        if quantization.active(self.config, arr):
            return self._compressed(arr, "allreduce", op)
        return self._shared.collective(self.rank, arr,
                                       ("allreduce", op))[self.rank]

    def reduce(self, tensor, root_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        return self._shared.collective(self.rank, tensor,
                                       ("reduce", op, root_rank))[self.rank]

    def broadcast(self, tensor, root_rank: int = 0):
        self._last_wire = None
        return self._shared.collective(self.rank, tensor,
                                       ("broadcast", root_rank))[self.rank]

    def allgather(self, tensor):
        self._last_wire = None
        return self._shared.collective(self.rank, tensor,
                                       ("allgather",))[self.rank]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        self._last_wire = None
        arr = np.asarray(tensor)
        if quantization.active(self.config, arr):
            return self._compressed(arr, "reducescatter", op)
        return self._shared.collective(self.rank, arr,
                                       ("reducescatter", op))[self.rank]

    def barrier(self):
        self._last_wire = None
        self._shared.collective(self.rank, np.zeros(()), ("barrier",))

    def send(self, tensor, dst_rank: int):
        self._last_wire = None
        self._shared.p2p_send(self.rank, dst_rank, tensor)

    def recv(self, src_rank: int):
        self._last_wire = None
        return self._shared.p2p_recv(self.rank, src_rank)

    def destroy(self):
        pass
