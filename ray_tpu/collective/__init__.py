from ray_tpu.collective.collective import (allgather, allreduce, barrier,
                                           broadcast, create_collective_group,
                                           destroy_collective_group,
                                           get_collective_group_size,
                                           get_rank, init_collective_group,
                                           is_group_initialized, recv, reduce,
                                           reducescatter, send, synchronize)
from ray_tpu.collective.types import Backend, CollectiveConfig, ReduceOp

__all__ = [
    "init_collective_group", "create_collective_group",
    "destroy_collective_group", "is_group_initialized", "get_rank",
    "get_collective_group_size", "allreduce", "allgather", "reducescatter",
    "broadcast", "reduce", "send", "recv", "barrier", "synchronize",
    "Backend", "CollectiveConfig", "ReduceOp",
]
