"""Multi-host tensor plane: state-KV-brokered ``jax.distributed`` rendezvous.

This is the piece that makes compiled collectives span daemon *processes*
(and, on real hardware, TPU hosts). The reference bootstraps its NCCL
communicators by parking an ``NCCLUniqueID`` in a named store actor that
every rank reads
(``python/ray/util/collective/collective_group/nccl_collective_group.py:54-95``)
and its torch trainers run ``dist.init_process_group`` with a rank-0
address (``python/ray/train/torch/config.py:54-96``). The TPU-native
equivalent is JAX's multi-controller runtime: rank 0 opens the coordination
service, every process calls ``jax.distributed.initialize``, and from then
on ``jax.devices()`` is the GLOBAL device set — collectives are compiled
into programs and ride ICI/DCN, not this control plane.

What the state-service KV brokers here, keyed by (group, epoch):
- the coordinator address (rank 0 binds a free port and publishes it),
- the world size (so mismatched joins fail loudly),
- a liveness epoch: after a failure the group re-forms under epoch+1, and
  stale processes shut their old runtime down before rejoining.

On CPU test clusters the same path runs over Gloo
(``jax_cpu_collectives_implementation``) with ``jax_num_cpu_devices``
virtual devices per process — the driver-validated dryrun analogue of a
multi-host TPU slice.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Optional

logger = logging.getLogger("ray_tpu")

KV_NS = b"tplane"

_lock = threading.Lock()
_active_plane: Optional[dict] = None  # {"group", "epoch", "world", "rank"}

_epoch_gauge = None


def _mark(event: str, group: str, epoch: int, **args) -> None:
    """Epoch lifecycle breadcrumb: a trace instant (when tracing is on)
    plus the ``tplane_epoch`` gauge, so a doctor correlating collective
    stalls can see exactly when a plane formed, re-formed, or went away
    (epoch -1).  Re-forms used to vanish silently."""
    global _epoch_gauge
    try:
        import ray_tpu.observability as _obs
        _obs.instant(f"tplane:{event}", cat="comms", group=group,
                     epoch=epoch, **args)
        if _epoch_gauge is None:
            from ray_tpu.observability.metric_names import TPLANE_EPOCH_GAUGE
            from ray_tpu.util import metrics
            # raylint: allow(data-race) idempotent lazy gauge init; the metrics registry dedups by name
            _epoch_gauge = metrics.Gauge(
                TPLANE_EPOCH_GAUGE,
                "active tensor-plane epoch per group (-1 once shut down)",
                ("group",))
        # Bounded cardinality: tag is the collective group name, a small
        # application-chosen set, never a per-task or per-object id.
        _epoch_gauge.set(float(epoch), tags={"group": group})
    except Exception:
        logger.debug("tplane lifecycle mark failed", exc_info=True)


def _runtime_and_kv(runtime=None):
    """The distributed runtime + its state-service KV."""
    if runtime is None:
        from ray_tpu._private import worker as _worker
        runtime = _worker.try_global_runtime()
    state = getattr(runtime, "state", None)
    if state is None:
        raise RuntimeError(
            "tensor plane needs a cluster (ray_tpu.init(address=...) or a "
            "host daemon); no state service in this process")
    return runtime, state


def _free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def current_plane() -> Optional[dict]:
    with _lock:
        return dict(_active_plane) if _active_plane else None


def init_tensor_plane(group_name: str, world_size: int, rank: int,
                      *, epoch: int = 0, num_cpu_devices: Optional[int] = None,
                      timeout_s: float = 60.0, runtime=None) -> dict:
    """Join the process-spanning tensor plane for ``group_name``/``epoch``.

    Must be called at most once per (group, epoch) per process; one process
    is one rank (the device-owner stance: libtpu is single-owner, so a TPU
    host contributes exactly one process). Re-joining under a newer epoch
    tears the previous JAX distributed runtime down first — that is how a
    group re-forms after a member died.
    """
    import jax

    runtime, state = _runtime_and_kv(runtime)
    key = f"{group_name}/{epoch}".encode()

    with _lock:
        global _active_plane
        if _active_plane is not None:
            if (_active_plane["group"] == group_name
                    and _active_plane["epoch"] == epoch):
                if _active_plane["rank"] != rank:
                    raise RuntimeError(
                        f"process already joined {group_name}@{epoch} as "
                        f"rank {_active_plane['rank']}, not {rank}")
                return dict(_active_plane)
            # Older (or different) plane: leave it before rejoining.
            _mark("reform", group_name, epoch,
                  old_group=_active_plane["group"],
                  old_epoch=_active_plane["epoch"])
            try:
                jax.distributed.shutdown()
            except Exception:
                logger.debug("jax.distributed.shutdown failed", exc_info=True)
            _active_plane = None

    # CPU test clusters: virtual devices + gloo collectives. Must land
    # before the backend initializes; harmless no-ops otherwise. Daemons
    # advertise their device count via RAY_TPU_TP_CPU_DEVICES (set by
    # ProcessCluster) so worker actors need no explicit argument.
    import os
    if num_cpu_devices is None:
        env_n = os.environ.get("RAY_TPU_TP_CPU_DEVICES")
        if env_n:
            num_cpu_devices = int(env_n)
    if num_cpu_devices is not None:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            if "xla_force_host_platform_device_count" not in os.environ.get(
                    "XLA_FLAGS", ""):
                jax.config.update("jax_num_cpu_devices",
                                  int(num_cpu_devices))
        except Exception:
            logger.warning("could not configure cpu collectives",
                           exc_info=True)

    if rank == 0:
        # Advertise the host peers can actually reach: the address this
        # daemon registered with the cluster (loopback only on
        # single-machine test clusters).
        addr = getattr(runtime, "address", "") or "127.0.0.1:0"
        host = addr.rsplit(":", 1)[0] or "127.0.0.1"
        coord = f"{host}:{_free_port(host)}"
        state.kv_put(key, f"{coord}|{world_size}".encode(),
                     overwrite=True, namespace=KV_NS)
    else:
        deadline = time.monotonic() + timeout_s
        coord = None
        while time.monotonic() < deadline:
            raw = state.kv_get(key, namespace=KV_NS)
            if raw:
                coord_s, world_s = raw.decode().split("|")
                if int(world_s) != world_size:
                    raise ValueError(
                        f"group {group_name}@{epoch} exists with world_size "
                        f"{world_s}, joined with {world_size}")
                coord = coord_s
                break
            time.sleep(0.02)
        if coord is None:
            raise TimeoutError(
                f"rank {rank}: no coordinator for {group_name}@{epoch} "
                f"within {timeout_s}s")

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=world_size, process_id=rank,
                               initialization_timeout=int(timeout_s))
    plane = {"group": group_name, "epoch": epoch, "world": world_size,
             "rank": rank, "coordinator": coord,
             "local_devices": len(jax.local_devices()),
             "global_devices": len(jax.devices())}
    with _lock:
        _active_plane = plane
    _mark("join", group_name, epoch, rank=rank, world=world_size,
          devices=plane["global_devices"])
    logger.info("tensor plane %s@%d up: rank %d/%d, %d global devices",
                group_name, epoch, rank, world_size,
                plane["global_devices"])
    return dict(plane)


def shutdown_tensor_plane():
    import jax
    with _lock:
        global _active_plane
        if _active_plane is None:
            return
        gone = _active_plane
        try:
            jax.distributed.shutdown()
        except Exception:
            logger.debug("jax.distributed.shutdown failed", exc_info=True)
        _active_plane = None
    _mark("shutdown", gone["group"], -1, last_epoch=gone["epoch"])
