"""ray_tpu — a TPU-native distributed computing framework.

A ground-up redesign of the capabilities of the Ray reference
(``/root/reference``, Ray 3.0.0.dev0) for TPU hardware: dynamic tasks and
actors with distributed futures, placement groups and pluggable scheduling,
an object store holding immutable host buffers and device-resident
``jax.Array`` descriptors, XLA-compiled collectives over ICI meshes instead
of NCCL calls, and Train/Tune/Data/Serve/RL library layers built on
``jax``/``pjit``/``shard_map``/Pallas.
"""

import os as _os

if _os.environ.get("RAY_TPU_LOCKWATCH"):
    # Must install before any submodule import so module-level locks are
    # wrapped too; see ray_tpu/devtools/lockwatch.py.
    from ray_tpu.devtools import lockwatch as _lockwatch
    _lockwatch.install()

from ray_tpu._private.config import _config  # noqa: F401
from ray_tpu._private.worker import (available_resources, cancel,
                                     cluster_resources, drain_node, get,
                                     get_actor, init, is_initialized, kill,
                                     nodes, put,
                                     register_named_actor_class,
                                     register_named_function,
                                     set_profiling_enabled,
                                     set_tracing_enabled, shutdown,
                                     timeline, wait)
from ray_tpu.actor import ActorClass, ActorHandle, ActorMethod  # noqa: F401
from ray_tpu.exceptions import (ActorDiedError, GetTimeoutError,  # noqa: F401
                                ObjectLostError, RayTpuError,
                                TaskCancelledError, TaskError)
from ray_tpu.object_ref import ObjectRef  # noqa: F401
from ray_tpu.remote_function import RemoteFunction, remote  # noqa: F401
from ray_tpu.runtime_context import get_runtime_context  # noqa: F401

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "available_resources", "cluster_resources",
    "drain_node",
    "register_named_actor_class",
    "register_named_function", "set_profiling_enabled",
    "set_tracing_enabled",
    "nodes", "timeline", "ObjectRef", "ActorClass", "ActorHandle",
    "ActorMethod",
    "RemoteFunction", "get_runtime_context",
    "RayTpuError", "TaskError", "ActorDiedError", "ObjectLostError",
    "GetTimeoutError", "TaskCancelledError",
]
