from ray_tpu.parallel.expert import moe_apply
from ray_tpu.parallel.mesh import (AXIS_ORDER, MeshConfig, build_mesh,
                                   single_axis_mesh)
from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from ray_tpu.parallel.sequence import ring_attention
from ray_tpu.parallel.sharding import (DEFAULT_RULES, ShardingRules,
                                       batch_sharding, replicated,
                                       shard_pytree)

__all__ = [
    "MeshConfig", "build_mesh", "single_axis_mesh", "AXIS_ORDER",
    "ShardingRules", "DEFAULT_RULES", "shard_pytree", "batch_sharding",
    "replicated", "pipeline_apply", "stack_stage_params", "ring_attention",
    "moe_apply",
]
