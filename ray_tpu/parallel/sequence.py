"""Sequence/context parallelism: ring attention over the ``seq`` mesh axis.

Long-context capability (net-new vs the reference, SURVEY §5.7): the sequence
dimension is sharded across devices; keys/values rotate around the ring via
``ppermute`` while each device's queries accumulate attention with streaming
(online-softmax) statistics, so peak memory per device is O(L/S · L/S block)
and the full O(L²) score matrix never materializes. The inner block kernel is
pluggable — the jnp einsum path compiles everywhere; the Pallas flash kernel
(``ray_tpu.ops.flash_attention``) slots in on TPU.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from ray_tpu._private.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn_update(q, k, v, m, l, acc, mask, scale):
    """One online-softmax accumulation step for a kv block.

    q: [B, Lq, H, D]; k/v: [B, Lk, H, D]; m/l: [B, H, Lq]; acc like q.
    mask: [Lq, Lk] boolean (True = attend) or None.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None].transpose(0, 2, 1, 3) + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, acc_new


@functools.lru_cache(maxsize=128)
def _ring_sharded(mesh: Mesh, axis: str, n_shards: int, causal: bool,
                  scale: float, batch_part: Optional[str]) -> Callable:
    """shard_map'd ring-attention step, memoized on its statics so repeat
    calls with the same mesh/config reuse one compiled callable."""

    def per_device(q_loc, k_loc, v_loc):
        my = jax.lax.axis_index(axis)
        B, Lq, H, D = q_loc.shape
        qf = q_loc.astype(jnp.float32)
        m = jnp.full((B, H, Lq), _NEG_INF, jnp.float32)
        l = jnp.zeros((B, H, Lq), jnp.float32)
        acc = jnp.zeros((B, Lq, H, D), jnp.float32)
        rows = jnp.arange(Lq)[:, None]
        cols = jnp.arange(k_loc.shape[1])[None, :]
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

        def step(carry, s):
            m, l, acc, kc, vc = carry
            src = (my - s) % n_shards  # which kv block we hold this round
            if causal:
                # src < my: full attention; src == my: lower-triangular;
                # src > my: fully masked.
                mask = jnp.where(
                    src < my, jnp.ones((Lq, k_loc.shape[1]), bool),
                    jnp.where(src == my, rows >= cols,
                              jnp.zeros((Lq, k_loc.shape[1]), bool)))
            else:
                mask = jnp.ones((Lq, k_loc.shape[1]), bool)
            m, l, acc = _block_attn_update(
                qf, kc.astype(jnp.float32), vc.astype(jnp.float32),
                m, l, acc, mask, scale)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return (m, l, acc, kc, vc), None

        (m, l, acc, _, _), _ = jax.lax.scan(
            step, (m, l, acc, k_loc, v_loc), jnp.arange(n_shards))
        out = acc / jnp.maximum(l, 1e-20)[..., None].transpose(0, 2, 1, 3)
        return out.astype(q_loc.dtype)

    spec = P(batch_part, axis, None, None)
    return shard_map(per_device, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis: str = "seq", causal: bool = True,
                   scale: Optional[float] = None,
                   data_axis: Optional[str] = "data") -> jax.Array:
    """Attention over sequence sharded on ``axis``.

    q, k, v: [batch, seqlen, heads, head_dim], seqlen sharded over ``axis``
    (and batch optionally over ``data_axis``). Returns same-sharded output.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n_shards = mesh.shape[axis]
    use_dp = (data_axis is not None and data_axis in mesh.axis_names
              and mesh.shape[data_axis] > 1)
    batch_part = data_axis if use_dp else None

    if n_shards == 1:
        L = q.shape[1]
        mask = (jnp.tril(jnp.ones((L, L), bool)) if causal else None)
        m = jnp.full(q.shape[:1] + (q.shape[2], q.shape[1]), _NEG_INF,
                     dtype=jnp.float32)
        l = jnp.zeros_like(m)
        acc = jnp.zeros(q.shape, jnp.float32)
        m, l, acc = _block_attn_update(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), m, l, acc, mask, scale)
        out = acc / jnp.maximum(l, 1e-20)[..., None].transpose(0, 2, 1, 3)
        return out.astype(q.dtype)

    fn = _ring_sharded(mesh, axis, n_shards, causal, float(scale),
                       batch_part)
    return fn(q, k, v)
