"""Pipeline parallelism: GPipe schedule compiled into one XLA program.

Stages live on the ``pipe`` mesh axis (outermost — its point-to-point
traffic tolerates DCN across slices, cf. PAPERS.md "Scaling Deep Learning
Training with MPMD Pipeline Parallelism"). Unlike a runtime scheduler pushing
microbatches between processes, the whole S-stage × M-microbatch schedule is
a ``lax.scan`` inside ``shard_map``: each step every stage applies its layer
block, then activations rotate one hop along the pipe axis via ``ppermute``.
Bubbles are the standard (S-1)/(M+S-1) fraction; scan keeps it one compiled
program with static shapes. Composes with data parallelism by sharding the
batch over ``data_axis``.

Capability net-new vs the reference (SURVEY §2.5: no PP anywhere).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from ray_tpu._private.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


@functools.lru_cache(maxsize=128)
def _pipeline_sharded(stage_fn: Callable, mesh: Mesh, axis: str,
                      n_stages: int, num_microbatches: int,
                      batch_part: Optional[str]) -> Callable:
    """shard_map'd GPipe schedule, memoized on its statics so repeat calls
    with the same mesh/stage config reuse one compiled callable."""

    def per_device(params, x_local):
        params = jax.tree.map(lambda p: p[0], params)  # this stage's slice
        stage = jax.lax.axis_index(axis)
        local_batch = x_local.shape[0]
        if local_batch % num_microbatches != 0:
            raise ValueError(
                f"per-device batch {local_batch} not divisible by "
                f"num_microbatches {num_microbatches}")
        mb_size = local_batch // num_microbatches
        mbs = x_local.reshape((num_microbatches, mb_size) + x_local.shape[1:])
        total_steps = num_microbatches + n_stages - 1
        out_buf = jnp.zeros_like(mbs)
        carry = jnp.zeros_like(mbs[0])

        def step(state, t):
            carry, out_buf = state
            # Stage 0 injects microbatch t; other stages consume the
            # activation that just arrived from the previous stage.
            inject = jax.lax.dynamic_index_in_dim(
                mbs, jnp.minimum(t, num_microbatches - 1), keepdims=False)
            inp = jnp.where(stage == 0, inject, carry)
            y = stage_fn(params, inp)
            # Last stage records its result for microbatch (t - S + 1).
            mb_idx = t - (n_stages - 1)
            valid = jnp.logical_and(stage == n_stages - 1, mb_idx >= 0)
            out_buf = jax.lax.cond(
                valid,
                lambda buf: jax.lax.dynamic_update_index_in_dim(
                    buf, y, jnp.maximum(mb_idx, 0), 0),
                lambda buf: buf,
                out_buf)
            # Rotate activations one hop forward along the pipe ring.
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            carry = jax.lax.ppermute(y, axis, perm)
            return (carry, out_buf), None

        (carry, out_buf), _ = jax.lax.scan(
            step, (carry, out_buf), jnp.arange(total_steps))
        # Replicate final outputs from the last stage onto every stage.
        out = jax.lax.psum(
            jnp.where(stage == n_stages - 1, out_buf, jnp.zeros_like(out_buf)),
            axis)
        return out.reshape((local_batch,) + x_local.shape[1:])

    x_spec = P(batch_part) if batch_part else P()
    return shard_map(per_device, mesh=mesh, in_specs=(P(axis), x_spec),
                     out_specs=x_spec, check_vma=False)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   mesh: Mesh, num_microbatches: int,
                   axis: str = "pipe",
                   data_axis: Optional[str] = "data") -> jax.Array:
    """Run ``stage_fn`` as a pipeline over mesh axis ``axis``.

    stage_fn(params_for_one_stage, activation[mb, ...]) -> activation
    stage_params: pytree whose leaves have leading dim = n_stages (sharded
        over ``axis``).
    x: [batch, ...] input (batch optionally sharded over ``data_axis``).
    Returns [batch, ...] output with the same sharding as the input batch.
    """
    n_stages = mesh.shape[axis]
    if n_stages == 1:
        return stage_fn(jax.tree.map(lambda p: p[0], stage_params), x)

    use_dp = (data_axis is not None and data_axis in mesh.axis_names
              and mesh.shape[data_axis] > 1)
    fn = _pipeline_sharded(stage_fn, mesh, axis, n_stages,
                           num_microbatches, data_axis if use_dp else None)
    return fn(stage_params, x)


def stack_stage_params(params_per_stage: list) -> Any:
    """Stack per-stage pytrees into leading-stage-dim arrays for sharding."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_per_stage)
