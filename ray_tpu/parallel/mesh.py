"""Device-mesh planning: the axes every parallelism strategy hangs off.

The reference has no intra-model parallelism (SURVEY §2.5, verified grep);
its scaling unit is the process (NCCL groups between actor processes). Here
the scaling unit is the **mesh axis**: DP/FSDP/TP/SP/PP/EP are all just named
axes of one ``jax.sharding.Mesh``, and XLA inserts the collectives. Axis
order follows the scaling-book recipe: model axes (tensor) fastest-varying so
their collectives ride nearest-neighbor ICI links; pipeline outermost so its
point-to-point traffic can cross slices (DCN) if needed.
"""

from __future__ import annotations
import logging

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger("ray_tpu")

# Canonical axis order, outermost (slowest-varying, DCN-tolerant) first.
AXIS_ORDER = ("pipe", "data", "fsdp", "expert", "seq", "tensor")


@dataclass
class MeshConfig:
    """Sizes for each parallelism axis; -1 = absorb remaining devices.

    data   — pure data parallelism (gradient psum)
    fsdp   — data parallelism with parameter sharding (ZeRO-3 style)
    tensor — tensor/model parallelism (Megatron-style, innermost on ICI)
    seq    — sequence/context parallelism (ring attention)
    pipe   — pipeline stages (outermost; DCN across slices)
    expert — expert parallelism (MoE all_to_all)
    """

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    def resolved(self, n_devices: int) -> "MeshConfig":
        sizes = {name: getattr(self, name) for name in AXIS_ORDER}
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild[0]] = n_devices // fixed
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {total} devices, have {n_devices}")
        out = MeshConfig(**sizes)
        return out

    def axis_sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, name) for name in AXIS_ORDER)

    def nontrivial_axes(self) -> List[str]:
        return [n for n in AXIS_ORDER if getattr(self, n) > 1]


def build_mesh(config: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build the named mesh. On real TPU topologies use
    ``mesh_utils.create_device_mesh`` so axis adjacency matches the physical
    torus; elsewhere (CPU tests) a plain reshape suffices."""
    devices = list(devices if devices is not None else jax.devices())
    cfg = config.resolved(len(devices))
    shape = cfg.axis_sizes()
    try:
        from jax.experimental import mesh_utils
        if devices and devices[0].platform == "tpu":
            arr = mesh_utils.create_device_mesh(shape, devices=devices)
        else:
            arr = np.array(devices).reshape(shape)
    except Exception as e:
        logger.debug("mesh_utils failed; naive reshape fallback: %s", e)
        arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def single_axis_mesh(axis: str = "data",
                     devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = {a: 1 for a in AXIS_ORDER}
    sizes[axis] = len(devices)
    return build_mesh(MeshConfig(**sizes), devices)
