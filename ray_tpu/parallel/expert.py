"""Expert parallelism: switch-style MoE with ``all_to_all`` dispatch.

Experts shard over the ``expert`` mesh axis; tokens route to their expert's
device via a single ``jax.lax.all_to_all`` (the EP pattern the reference has
no analogue for — its parallelism stops at process-level DP, SURVEY §2.5).
Top-1 (switch) routing with a capacity limit; dropped tokens pass through the
residual path.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from ray_tpu._private.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


@functools.lru_cache(maxsize=128)
def _moe_sharded(expert_fn: Callable, mesh: Mesh, axis: str,
                 n_exp_total: int, n_shards: int, exp_per_shard: int,
                 capacity_factor: float) -> Callable:
    """shard_map'd MoE dispatch, memoized on its statics so repeat calls
    with the same mesh/routing config reuse one compiled callable."""

    def per_device(x_loc, rw, params):
        tokens, d = x_loc.shape
        capacity = max(1, int(capacity_factor * tokens / n_exp_total))
        gates = jax.nn.softmax(x_loc @ rw, axis=-1)            # [T, E]
        expert_idx = jnp.argmax(gates, axis=-1)                # [T]
        gate_val = jnp.take_along_axis(
            gates, expert_idx[:, None], axis=-1)[:, 0]         # [T]
        # Position of each token within its expert's capacity buffer.
        onehot = jax.nn.one_hot(expert_idx, n_exp_total, dtype=jnp.int32)
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot  # [T, E]
        pos = jnp.sum(pos_in_expert, axis=-1)                  # [T]
        keep = pos < capacity
        # Scatter tokens into [E, capacity, d] dispatch buffer.
        disp = jnp.zeros((n_exp_total, capacity, d), x_loc.dtype)
        tok_ids = jnp.arange(tokens)
        disp = disp.at[expert_idx, jnp.clip(pos, 0, capacity - 1)].add(
            jnp.where(keep[:, None], x_loc, 0.0))
        # Exchange: [E, cap, d] -> experts grouped by owning shard.
        disp = disp.reshape(n_shards, exp_per_shard, capacity, d)
        recv = jax.lax.all_to_all(disp, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        # recv: [n_shards, exp_per_shard, capacity, d] — all shards' tokens
        # destined for MY experts. Flatten senders into the capacity dim.
        recv = recv.transpose(1, 0, 2, 3).reshape(
            exp_per_shard, n_shards * capacity, d)
        # in_specs P(axis) already hands this device its expert slice
        # (leading dim == exp_per_shard).
        out = jax.vmap(expert_fn)(params, recv)
        # Undo: [exp_per_shard, n_shards, capacity, d] -> all_to_all back.
        out = out.reshape(exp_per_shard, n_shards, capacity, d).transpose(
            1, 0, 2, 3)
        back = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        back = back.reshape(n_exp_total, capacity, d)
        # Gather each token's expert output; dropped tokens get zeros.
        y = back[expert_idx, jnp.clip(pos, 0, capacity - 1)]
        y = jnp.where(keep[:, None], y, 0.0)
        return x_loc + gate_val[:, None] * y  # residual + gated expert out

    return shard_map(per_device, mesh=mesh,
                     in_specs=(P(), P(), P(axis)),
                     out_specs=P(), check_vma=False)


def moe_apply(x: jax.Array, router_weights: jax.Array, expert_params: Any,
              expert_fn: Callable, mesh: Mesh, axis: str = "expert",
              capacity_factor: float = 1.25) -> jax.Array:
    """x: [tokens, d_model] (replicated over ``axis``); router_weights:
    [d_model, n_experts]; expert_params leaves have leading dim n_experts
    (sharded over ``axis``). Returns [tokens, d_model]."""
    n_exp_total = router_weights.shape[-1]
    n_shards = mesh.shape[axis]
    if n_exp_total % n_shards != 0:
        raise ValueError(f"{n_exp_total} experts not divisible over "
                         f"{n_shards} expert shards")
    fn = _moe_sharded(expert_fn, mesh, axis, n_exp_total, n_shards,
                      n_exp_total // n_shards, capacity_factor)
    return fn(x, router_weights, expert_params)
