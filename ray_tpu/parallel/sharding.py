"""Logical-axis sharding rules: annotate once, let XLA insert collectives.

Parameters and activations are described by *logical* axis names
("embed", "heads", "batch", ...); a ``ShardingRules`` table maps each to a
mesh axis (or replication). This is the pjit/scaling-book methodology —
shardings are data, not code, so switching DP↔FSDP↔TP↔SP is a config edit,
not a rewrite. (Capability net-new vs the reference; SURVEY §2.5.)
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("ray_tpu")

MeshAxes = Union[None, str, Tuple[str, ...]]

# Logical-axis names already warned about this process — a typo surfaces
# once, loudly, instead of flooding every step (R27 is the static half).
_warned_axes: set = set()


DEFAULT_RULES: Dict[str, MeshAxes] = {
    # activations
    "batch": ("data", "fsdp"),      # per-example axis: all data-parallel axes
    "seq": "seq",                   # sequence/context parallelism
    "act_embed": None,              # activation feature dim stays replicated
    "act_heads": "tensor",
    # parameters
    "embed": "fsdp",                # ZeRO-3: shard params along embed over fsdp
    "vocab": "tensor",
    "heads": "tensor",              # attention heads over tensor axis
    "kv": None,
    "mlp": "tensor",                # ffn hidden over tensor axis
    # mixture of experts
    "expert": "expert",
    # pipeline
    "stage": "pipe",
}


@dataclass(frozen=True)
class ShardingRules:
    rules: Dict[str, MeshAxes] = field(default_factory=lambda: dict(DEFAULT_RULES))

    def with_overrides(self, **overrides: MeshAxes) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(overrides)
        return ShardingRules(merged)

    def spec(self, logical_axes: Tuple[Optional[str], ...],
             strict: bool = False) -> P:
        """PartitionSpec for a tensor described by logical axis names.

        An axis name missing from the table replicates that dimension.
        With ``strict=True`` an *unknown* name (as opposed to one mapped
        to ``None`` on purpose) raises instead — a one-character typo
        would otherwise silently replicate a tensor; the default path
        logs a one-shot warning per unknown name.
        """
        parts = []
        used = set()
        for ax in logical_axes:
            if ax is None:
                parts.append(None)
                continue
            if ax not in self.rules:
                if strict:
                    raise ValueError(
                        f"unknown logical axis {ax!r}: not in this "
                        f"ShardingRules table (known: "
                        f"{', '.join(sorted(self.rules))}); without "
                        "strict=True this dimension would silently "
                        "replicate")
                if ax not in _warned_axes:
                    _warned_axes.add(ax)
                    logger.warning(
                        "ShardingRules: unknown logical axis %r replicates "
                        "its dimension (known: %s); pass strict=True to "
                        "raise on typos", ax,
                        ", ".join(sorted(self.rules)))
                parts.append(None)
                continue
            mesh_axes = self.rules.get(ax)
            if mesh_axes is None:
                parts.append(None)
                continue
            if isinstance(mesh_axes, str):
                mesh_axes = (mesh_axes,)
            fresh = tuple(a for a in mesh_axes if a not in used)
            used.update(fresh)
            if not fresh:
                parts.append(None)
            elif len(fresh) == 1:
                parts.append(fresh[0])
            else:
                parts.append(fresh)
        return P(*parts)

    def sharding(self, mesh: Mesh,
                 logical_axes: Tuple[Optional[str], ...],
                 strict: bool = False) -> NamedSharding:
        """NamedSharding on *mesh*, dropping mesh axes sized 1 there.

        With ``strict=True``, unknown logical names raise (see ``spec``)
        and so does a rule naming a mesh axis this mesh does not have —
        geometry drift between the rules table and the mesh.  Size-1
        axes are still dropped silently in both modes: a collapsed axis
        is legitimate single-way parallelism, not a typo.
        """
        spec = self.spec(logical_axes, strict=strict)
        # Drop axes not present in (or sized 1 on) this mesh.
        cleaned = []
        for part in spec:
            if part is None:
                cleaned.append(None)
            elif isinstance(part, tuple):
                missing = [a for a in part if a not in mesh.axis_names]
                if missing and strict:
                    raise ValueError(
                        f"rules name mesh axes {missing} absent from this "
                        f"mesh (axes: {', '.join(mesh.axis_names)})")
                keep = tuple(a for a in part if a in mesh.axis_names
                             and mesh.shape[a] > 1)
                cleaned.append(keep if keep else None)
            else:
                if part not in mesh.axis_names and strict:
                    raise ValueError(
                        f"rules name mesh axis {part!r} absent from this "
                        f"mesh (axes: {', '.join(mesh.axis_names)})")
                cleaned.append(part if part in mesh.axis_names
                               and mesh.shape[part] > 1 else None)
        return NamedSharding(mesh, P(*cleaned))


def _axes_mismatch_path(tree: Any, axes: Any,
                        path: str = "") -> Optional[str]:
    """First path where ``axes`` stops mirroring ``tree``, else None.

    Containers (dict/list/tuple) of ``tree`` must be matched by the same
    container shape in ``axes``; at a ``tree`` leaf any axes value is
    acceptable (tuples of names, a single name, or None).
    """
    if isinstance(tree, dict):
        if not isinstance(axes, dict):
            return (f"{path or '<root>'}: tree has a dict, axes_tree has "
                    f"{type(axes).__name__}")
        if set(tree) != set(axes):
            missing = sorted(set(tree) - set(axes))
            extra = sorted(set(axes) - set(tree))
            detail = []
            if missing:
                detail.append(f"missing keys {missing}")
            if extra:
                detail.append(f"extra keys {extra}")
            return f"{path or '<root>'}: {', '.join(detail)}"
        for k in sorted(tree):
            sub = _axes_mismatch_path(tree[k], axes[k], f"{path}[{k!r}]")
            if sub is not None:
                return sub
        return None
    if isinstance(tree, (list, tuple)):
        if not isinstance(axes, type(tree)) or len(axes) != len(tree):
            return (f"{path or '<root>'}: tree has {type(tree).__name__} "
                    f"of {len(tree)}, axes_tree has "
                    f"{type(axes).__name__} of "
                    f"{len(axes) if isinstance(axes, (list, tuple)) else 1}")
        for i, (t, a) in enumerate(zip(tree, axes)):
            sub = _axes_mismatch_path(t, a, f"{path}[{i}]")
            if sub is not None:
                return sub
    return None


def shard_pytree(tree: Any, axes_tree: Any, mesh: Mesh,
                 rules: Optional[ShardingRules] = None,
                 strict: bool = False) -> Any:
    """Device-put every leaf with the sharding derived from its logical axes.

    ``axes_tree`` mirrors ``tree`` with tuples of logical axis names; a
    mis-shaped ``axes_tree`` raises naming the first mismatched path
    instead of jax.tree.map's opaque structure dump.  ``strict`` is
    forwarded to :meth:`ShardingRules.sharding`.
    """
    rules = rules or ShardingRules()

    def _place(leaf, axes):
        return jax.device_put(leaf, rules.sharding(mesh, axes,
                                                   strict=strict))

    try:
        return jax.tree.map(_place, tree, axes_tree,
                            is_leaf=lambda x: x is None)
    except (ValueError, TypeError) as e:
        where = _axes_mismatch_path(tree, axes_tree)
        if where is None:
            raise
        raise ValueError(
            f"axes_tree does not mirror tree at {where}") from e


def batch_sharding(mesh: Mesh, rules: Optional[ShardingRules] = None,
                   ndim: int = 2) -> NamedSharding:
    """Sharding for a [batch, ...] input array."""
    rules = rules or ShardingRules()
    return rules.sharding(mesh, ("batch",) + (None,) * (ndim - 1))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
