"""Logical-axis sharding rules: annotate once, let XLA insert collectives.

Parameters and activations are described by *logical* axis names
("embed", "heads", "batch", ...); a ``ShardingRules`` table maps each to a
mesh axis (or replication). This is the pjit/scaling-book methodology —
shardings are data, not code, so switching DP↔FSDP↔TP↔SP is a config edit,
not a rewrite. (Capability net-new vs the reference; SURVEY §2.5.)
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]


DEFAULT_RULES: Dict[str, MeshAxes] = {
    # activations
    "batch": ("data", "fsdp"),      # per-example axis: all data-parallel axes
    "seq": "seq",                   # sequence/context parallelism
    "act_embed": None,              # activation feature dim stays replicated
    "act_heads": "tensor",
    # parameters
    "embed": "fsdp",                # ZeRO-3: shard params along embed over fsdp
    "vocab": "tensor",
    "heads": "tensor",              # attention heads over tensor axis
    "kv": None,
    "mlp": "tensor",                # ffn hidden over tensor axis
    # mixture of experts
    "expert": "expert",
    # pipeline
    "stage": "pipe",
}


@dataclass(frozen=True)
class ShardingRules:
    rules: Dict[str, MeshAxes] = field(default_factory=lambda: dict(DEFAULT_RULES))

    def with_overrides(self, **overrides: MeshAxes) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(overrides)
        return ShardingRules(merged)

    def spec(self, logical_axes: Tuple[Optional[str], ...]) -> P:
        """PartitionSpec for a tensor described by logical axis names."""
        parts = []
        used = set()
        for ax in logical_axes:
            if ax is None:
                parts.append(None)
                continue
            mesh_axes = self.rules.get(ax)
            if mesh_axes is None:
                parts.append(None)
                continue
            if isinstance(mesh_axes, str):
                mesh_axes = (mesh_axes,)
            fresh = tuple(a for a in mesh_axes if a not in used)
            used.update(fresh)
            if not fresh:
                parts.append(None)
            elif len(fresh) == 1:
                parts.append(fresh[0])
            else:
                parts.append(fresh)
        return P(*parts)

    def sharding(self, mesh: Mesh,
                 logical_axes: Tuple[Optional[str], ...]) -> NamedSharding:
        spec = self.spec(logical_axes)
        # Drop axes not present in (or sized 1 on) this mesh.
        cleaned = []
        for part in spec:
            if part is None:
                cleaned.append(None)
            elif isinstance(part, tuple):
                keep = tuple(a for a in part if a in mesh.axis_names
                             and mesh.shape[a] > 1)
                cleaned.append(keep if keep else None)
            else:
                cleaned.append(part if part in mesh.axis_names
                               and mesh.shape[part] > 1 else None)
        return NamedSharding(mesh, P(*cleaned))


def shard_pytree(tree: Any, axes_tree: Any, mesh: Mesh,
                 rules: Optional[ShardingRules] = None) -> Any:
    """Device-put every leaf with the sharding derived from its logical axes.

    ``axes_tree`` mirrors ``tree`` with tuples of logical axis names.
    """
    rules = rules or ShardingRules()

    def _place(leaf, axes):
        return jax.device_put(leaf, rules.sharding(mesh, axes))

    return jax.tree.map(_place, tree, axes_tree,
                        is_leaf=lambda x: x is None)


def batch_sharding(mesh: Mesh, rules: Optional[ShardingRules] = None,
                   ndim: int = 2) -> NamedSharding:
    """Sharding for a [batch, ...] input array."""
    rules = rules or ShardingRules()
    return rules.sharding(mesh, ("batch",) + (None,) * (ndim - 1))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
