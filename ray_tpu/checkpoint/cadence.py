"""Risk-tuned checkpoint cadence: ``checkpoint_frequency="auto"``.

A fixed checkpoint frequency is wrong in both directions on a
preemptible fleet: too sparse and every preemption replays a long tail
of lost steps (``restart_downtime`` in the goodput ledger), too dense
and the job pays ``ckpt_stall`` every few steps for failures that never
come.  The optimum moves with the *fleet hazard rate* — which the
autoscaler's :mod:`ray_tpu.autoscaler.hazard` estimator measures and
publishes — so cadence must be solved, not configured.

The solver is the classic Young–Daly optimum. With

- ``M`` — mean time between failures, ``3600 / hazard_rate_per_hour``,
  less the restart cost a failure also charges (``restart_downtime``
  observed by the trainer's elastic-restart loop),
- ``delta`` — the per-checkpoint overhead the *step loop* observes
  (synchronous enqueue share plus measured ``ckpt_stall``),

the optimal wall-clock interval between checkpoints is
``T_opt = sqrt(2 * delta * M)``, and the interval in *steps* is
``T_opt / step_cost_s`` — so rising hazard or rising step cost both
shrink the step interval (checkpoint more often), while a costlier
checkpoint stretches it.  The result is clamped to
``[checkpoint_cadence_min_steps, checkpoint_cadence_max_steps]``.

:class:`CadenceController` wraps the solver with measurement (EWMA step
cost from ``session.report`` inter-arrival, EWMA checkpoint overhead
from engine-save enqueue time plus the ledger's ``ckpt_stall`` delta)
and re-solves every ``checkpoint_cadence_refresh_steps`` reports, so a
hazard change mid-run re-tunes the cadence within one refresh window.
"""

from __future__ import annotations

import logging
import math
from typing import Callable, Optional

from ray_tpu._private.config import _config

logger = logging.getLogger("ray_tpu")


def solve_interval_steps(hazard_rate_per_hour: float, step_cost_s: float,
                         ckpt_cost_s: float, restart_cost_s: float = 0.0,
                         min_steps: Optional[int] = None,
                         max_steps: Optional[int] = None) -> int:
    """Young–Daly checkpoint interval, in steps (see module docstring).

    Pure and total: zero/negative hazard means "failures are not
    expected" and returns the ceiling; a degenerate step cost returns
    the ceiling too (there is no step clock to count in)."""
    if min_steps is None:
        min_steps = _config.get("checkpoint_cadence_min_steps")
    if max_steps is None:
        max_steps = _config.get("checkpoint_cadence_max_steps")
    min_steps = max(1, int(min_steps))
    max_steps = max(min_steps, int(max_steps))
    if hazard_rate_per_hour <= 0.0 or step_cost_s <= 0.0:
        return max_steps
    mtbf_s = 3600.0 / hazard_rate_per_hour
    # A failure costs its restart too: the budget an interval gambles
    # against is the useful time between failures, not the raw MTBF.
    useful_mtbf_s = max(step_cost_s, mtbf_s - max(0.0, restart_cost_s))
    t_opt_s = math.sqrt(2.0 * max(1e-3, ckpt_cost_s) * useful_mtbf_s)
    return max(min_steps, min(max_steps, round(t_opt_s / step_cost_s)))


def kv_hazard_source() -> Callable[[], float]:
    """Default fleet-hazard feed for worker sessions: the rate the
    autoscaler's estimator publishes into the state KV, falling back to
    the ``hazard_rate_floor_per_hour`` prior when nothing was published
    (cold fleet, in-process runtime, state unreachable)."""
    def read() -> float:
        try:
            from ray_tpu._private import worker as _worker
            state = getattr(_worker.global_worker().runtime, "state", None)
            if state is not None:
                from ray_tpu.autoscaler import hazard as _hazard
                rate = _hazard.read_fleet_rate(state)
                if rate is not None:
                    return rate
        except Exception as e:  # noqa: BLE001
            logger.debug("cadence: hazard read failed: %s", e)
        return _config.get("hazard_rate_floor_per_hour")
    return read


class CadenceController:
    """Measured inputs + periodic re-solve for one training session.

    ``observe_step`` feeds the inter-report wall time, ``observe_ckpt``
    the synchronous cost of each engine save; ``interval_steps()`` is
    consulted once per reported checkpoint and re-solves every
    ``checkpoint_cadence_refresh_steps`` observed steps. Single-threaded
    by construction: all calls come from the session's train loop.
    """

    #: EWMA smoothing for measured costs — new samples count this much.
    ALPHA = 0.3

    def __init__(self, hazard_source: Optional[Callable[[], float]] = None,
                 restart_cost_s: float = 0.0,
                 min_steps: Optional[int] = None,
                 max_steps: Optional[int] = None,
                 refresh_steps: Optional[int] = None):
        self._hazard = hazard_source or kv_hazard_source()
        self.restart_cost_s = float(restart_cost_s)
        self._min = min_steps
        self._max = max_steps
        self._refresh = (refresh_steps if refresh_steps is not None
                         else _config.get("checkpoint_cadence_refresh_steps"))
        self._ewma_step_s: Optional[float] = None
        self._ewma_ckpt_s: Optional[float] = None
        self._steps_since_solve = 0
        self._saves_since_solve = 0
        self._last_stall_s = 0.0
        self.last_hazard_per_hour: Optional[float] = None
        self.last_interval: Optional[int] = None

    def observe_step(self, seconds: float) -> None:
        if seconds <= 0.0:
            return
        prev = self._ewma_step_s
        self._ewma_step_s = (seconds if prev is None
                             else prev + self.ALPHA * (seconds - prev))
        self._steps_since_solve += 1

    def observe_ckpt(self, seconds: float) -> None:
        if seconds < 0.0:
            return
        prev = self._ewma_ckpt_s
        self._ewma_ckpt_s = (seconds if prev is None
                             else prev + self.ALPHA * (seconds - prev))
        self._saves_since_solve += 1

    def _ckpt_cost_s(self) -> float:
        """Per-checkpoint overhead: the measured synchronous enqueue share
        plus the goodput ledger's ``ckpt_stall`` growth amortized over the
        saves that caused it (queue-full backpressure the enqueue timing
        alone understates)."""
        cost = self._ewma_ckpt_s if self._ewma_ckpt_s is not None else 0.1
        try:
            from ray_tpu.observability import goodput
            jobs = goodput.snapshot().get("jobs") or {}
            stall = sum(float((rec.get("cats") or {}).get("ckpt_stall") or 0.0)
                        for rec in jobs.values())
        except Exception as e:  # noqa: BLE001
            logger.debug("cadence: ledger read failed: %s", e)
            return cost
        delta = stall - self._last_stall_s
        if delta > 0.0 and self._saves_since_solve > 0:
            cost += delta / self._saves_since_solve
        self._last_stall_s = max(self._last_stall_s, stall)
        return cost

    def interval_steps(self) -> int:
        """Current steps-between-checkpoints; re-solves when the refresh
        window elapsed (or on first use).  The autopilot's cluster-level
        override wins when set: its cadence policy solves the same
        Young-Daly optimum from the *fleet* hazard feed and actuates it
        through the journaled actuator layer, so a cluster whose hazard
        just spiked retunes every session at once — still clamped to
        the operator's cadence bounds here."""
        override = int(_config.get("checkpoint_cadence_autopilot_steps"))
        if override > 0:
            lo = max(1, int(self._min
                            if self._min is not None
                            else _config.get("checkpoint_cadence_min_steps")))
            hi = max(lo, int(self._max
                             if self._max is not None
                             else _config.get("checkpoint_cadence_max_steps")))
            self.last_interval = max(lo, min(hi, override))
            return self.last_interval
        if (self.last_interval is not None
                and self._steps_since_solve < max(1, self._refresh)):
            return self.last_interval
        hazard = max(0.0, float(self._hazard()))
        interval = solve_interval_steps(
            hazard,
            self._ewma_step_s if self._ewma_step_s is not None else 1.0,
            self._ckpt_cost_s(),
            restart_cost_s=self.restart_cost_s,
            min_steps=self._min, max_steps=self._max)
        if interval != self.last_interval:
            logger.info("checkpoint cadence: every %d step(s) (hazard "
                        "%.2f/h, step %.3fs, ckpt %.3fs)", interval,
                        hazard, self._ewma_step_s or 1.0,
                        self._ewma_ckpt_s or 0.1)
        self.last_hazard_per_hour = hazard
        self.last_interval = interval
        self._steps_since_solve = 0
        self._saves_since_solve = 0
        return interval
