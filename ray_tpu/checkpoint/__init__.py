"""ray_tpu.checkpoint — async sharded checkpointing with crash-atomic
commit, content-hash dedup, and reshard-on-restore.

See engine.py for the save/commit pipeline and ARCHITECTURE.md
"Checkpointing & elastic restore" for the on-disk format contract.
"""

from ray_tpu.checkpoint.cadence import CadenceController, solve_interval_steps
from ray_tpu.checkpoint.engine import (CheckpointEngine, CheckpointRef,
                                       EngineStats, SaveHandle, load)
from ray_tpu.checkpoint.manifest import (CheckpointCorruption,
                                         CheckpointError, CheckpointNotFound,
                                         Manifest, ShardIndex,
                                         list_manifest_names, read_manifest,
                                         resolve_latest)

__all__ = [
    "CadenceController", "solve_interval_steps",
    "CheckpointEngine", "CheckpointRef", "EngineStats", "SaveHandle", "load",
    "CheckpointError", "CheckpointCorruption", "CheckpointNotFound",
    "Manifest", "ShardIndex", "list_manifest_names", "read_manifest",
    "resolve_latest",
]
