"""Async sharded checkpoint engine.

Save path (per rank)::

    caller thread                     writer thread (daemon, bounded queue)
    -------------                     -----------------------------------
    flatten pytree                    hash each array (sha256 of
    device->host (np.asarray)    -->    dtype|shape|bytes) = chunk id
    enqueue job, return handle        dedup: chunk file exists -> skip
                                      else gather-write RTF5 frame + rename
                                      write shard index into pending/
                                      rank 0 only: wait for all ranks'
                                        shard indexes, then COMMIT

``save()`` returns as soon as the device->host copy is done; disk I/O
overlaps the next training step. The bounded queue (``checkpoint_queue_depth``)
applies backpressure instead of buffering unbounded host copies.

Two raw-speed mechanisms sit on the write path:

- **hash/write worker pool** (``checkpoint_io_workers``): sha256 and the
  chunk-file write of independent leaves overlap instead of running
  leaf-after-leaf on the writer thread (cold save is hash-bound on one
  core, I/O-bound on spinning storage — either way the overlap wins).
  ``<=1`` degrades to the serial path. Chaos choke points keep firing on
  the writer thread in submission order, so fault schedules stay
  deterministic regardless of worker interleaving.
- **content-hash cache**: leaves whose buffers provably can't mutate —
  jax arrays (immutable by API) and numpy arrays frozen with
  ``writeable=False`` — memoize their chunk id by buffer identity, so a
  warm save of an unchanged tree skips the device->host copy, the hash,
  AND the write, and commits in about a millisecond. Writeable numpy
  buffers are never cached: they re-hash every save by design.

Commit protocol (rank 0): verify every referenced chunk exists -> write
manifest (tmp+fsync+rename) -> advance LATEST -> best-effort register in the
state service -> prune to ``num_to_keep`` + GC. A crash at any point leaves
the previous or the new checkpoint fully readable (see manifest.py).

Restore reshards when the world size changed: replicated saves hand any
shard to any rank; axis-sharded saves are reassembled into global arrays
from the per-shard offsets recorded at commit, then re-split
``lo = r*dim//W, hi = (r+1)*dim//W`` along the shard axis for the new world.
Which leaves are axis-split is DECLARED at save time (``shard_paths``
fnmatch patterns against the "/"-joined leaf path) and stamped into each
shard index — never inferred from data, so per-rank-distinct but logically
replicated leaves (RNG keys, rank-local counters) of matching shapes can't
be misread as one split array. Undeclared leaves restore replicated
(rank 0's copy when the world changes).

Chaos choke points: ``checkpoint.write`` (per chunk, labels path/rank),
``checkpoint.commit`` (labels stage=manifest|latest, step), and
``checkpoint.restore`` (labels manifest, rank).
"""

from __future__ import annotations

import fnmatch
import json
import logging
import os
import queue
import re
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu import chaos, observability
from ray_tpu.observability import goodput, perf
from ray_tpu._private.config import _config
from ray_tpu._private.framing import FramedPayload, dumps_framed, loads_framed
from ray_tpu.checkpoint import manifest as mf
from ray_tpu.checkpoint.manifest import (ArrayEntry, CheckpointCorruption,
                                         CheckpointError, CheckpointNotFound,
                                         Manifest, ShardIndex)

logger = logging.getLogger("ray_tpu")


class _Slot:
    """Marks where an array leaf was lifted out of the skeleton pytree."""

    __slots__ = ("slot",)

    def __init__(self, slot: int):
        self.slot = slot

    def __reduce__(self):
        return (_Slot, (self.slot,))


def _is_array(x: Any) -> bool:
    if isinstance(x, np.ndarray):
        return True
    cls = type(x)
    return cls.__module__.startswith("jax") and hasattr(x, "dtype") \
        and hasattr(x, "shape")


def _extract_arrays(value: Any, path: Tuple[str, ...],
                    out: List[Any],
                    make_leaf: Optional[Callable[[str, Any], Any]] = None
                    ) -> Any:
    """Replace array leaves with _Slot markers; collect (path, host array)
    — or whatever ``make_leaf(path, leaf)`` produces (the engine passes a
    hash-cache-aware builder). np.asarray is the device->host transfer
    for jax.Array leaves."""
    if isinstance(value, dict):
        return {k: _extract_arrays(v, path + (str(k),), out, make_leaf)
                for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        seq = [_extract_arrays(v, path + (str(i),), out, make_leaf)
               for i, v in enumerate(value)]
        return tuple(seq) if isinstance(value, tuple) else seq
    if _is_array(value):
        slot = len(out)
        if make_leaf is not None:
            out.append(make_leaf("/".join(path), value))
        else:
            out.append(("/".join(path),
                        np.ascontiguousarray(np.asarray(value))))
        return _Slot(slot)
    return value


def _inject_arrays(value: Any, slots: Dict[int, np.ndarray]) -> Any:
    if isinstance(value, dict):
        return {k: _inject_arrays(v, slots) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        seq = [_inject_arrays(v, slots) for v in value]
        return tuple(seq) if isinstance(value, tuple) else seq
    if isinstance(value, _Slot):
        return slots[value.slot]
    return value


def _hash_array(arr: np.ndarray) -> str:
    try:
        raw = memoryview(arr).cast("B")
    except (TypeError, ValueError):
        raw = arr.tobytes()
    return mf.hash_bytes(arr.dtype.str, json.dumps(list(arr.shape)), raw)


# -- chunk serving (restore-side striped remote fetch) ------------------------
#
# A restoring rank whose root is NOT the saver's shared filesystem pulls
# missing chunks from a peer over the FETCH_OBJECT bulk lane
# (arena_key="ckpt:<sha256>" — see distributed._handle_fetch_ckpt_chunk).
# Every engine registers its root here; chunks are content-addressed and
# immutable, so serving any registered root that holds the id is correct.

_serve_lock = threading.Lock()
_SERVE_ROOTS: "set[str]" = set()
_CHUNK_ID_RE = re.compile(r"[0-9a-f]{64}\Z")


def register_serve_root(root: str) -> None:
    with _serve_lock:
        _SERVE_ROOTS.add(os.path.abspath(root))


def read_served_chunk(chunk_id: str) -> Optional[bytes]:
    """Bytes of a locally-held chunk, or None. The id is validated as a
    bare content hash before touching the filesystem — the wire value
    can never become a path traversal."""
    if not _CHUNK_ID_RE.fullmatch(chunk_id):
        return None
    with _serve_lock:
        roots = list(_SERVE_ROOTS)
    for root in roots:
        path = os.path.join(root, mf.chunk_relpath(chunk_id))
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            continue
    return None


# -- warm-save content-hash cache ---------------------------------------------

def _cacheable(x: Any) -> bool:
    """Leaves whose bytes provably can't change behind the cache's back:
    jax arrays (immutable by API) and numpy arrays explicitly frozen with
    ``writeable=False``. The flag is re-checked at every lookup, so
    thawing a frozen array drops it from the cache; a writeable buffer is
    never trusted in the first place."""
    if isinstance(x, np.ndarray):
        return not x.flags.writeable
    return _is_array(x)


class _HashCache:
    """Chunk-id memo keyed on leaf buffer identity (id + liveness).

    A warm save of an unchanged tree must not pay the device->host copy,
    the sha256, or the chunk write again — for an immutable buffer the
    content hash is a function of its identity. Each entry carries a
    weakref: a freed buffer (whose id() the allocator may hand to a new
    object) evicts its own entry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, tuple] = {}  # raylint: guarded-by(self._lock)

    def lookup(self, x: Any) -> Optional[tuple]:
        """(chunk_id, nbytes, dtype_str, shape) or None."""
        if not _cacheable(x):
            return None
        with self._lock:
            ent = self._entries.get(id(x))
        if ent is None or ent[0]() is not x:
            return None
        return ent[1:]

    def remember(self, x: Any, chunk_id: str, nbytes: int,
                 dtype: str, shape: List[int]) -> None:
        if not _cacheable(x):
            return
        key = id(x)

        def _evict(_ref, _key=key, _self_ref=weakref.ref(self)):
            cache = _self_ref()
            if cache is not None:
                with cache._lock:
                    cache._entries.pop(_key, None)

        try:
            ref = weakref.ref(x, _evict)
        except TypeError:
            return  # leaf type doesn't support weakrefs: never cached
        with self._lock:
            self._entries[key] = (ref, chunk_id, nbytes, dtype, list(shape))


@dataclass
class _LeafTask:
    """One array leaf's unit of save work: either ``arr`` holds the host
    copy to hash+write, or ``chunk_id`` names the already-known chunk (a
    hash-cache hit — no host copy was ever made)."""

    path: str
    nbytes: int
    dtype: str
    shape: List[int]
    arr: Optional[np.ndarray] = None
    chunk_id: Optional[str] = None
    origin: Any = None   # original leaf, for the cache's remember()


@dataclass
class EngineStats:
    saves: int = 0
    commits: int = 0
    chunks_written: int = 0
    chunk_bytes_written: int = 0
    chunks_deduped: int = 0
    bytes_deduped: int = 0
    chunks_gced: int = 0


class SaveHandle:
    """Completion token for one rank's async save. ``result()`` returns the
    committed manifest filename on rank 0, None on other ranks."""

    def __init__(self, step: int, rank: int):
        self.step = step
        self.rank = rank
        self._done = threading.Event()
        self._manifest_name: Optional[str] = None
        self._error: Optional[BaseException] = None

    def _finish(self, manifest_name: Optional[str],
                error: Optional[BaseException]) -> None:
        # raylint: allow(data-race) written before _done.set(); result() reads only after a successful wait
        self._manifest_name = manifest_name
        self._error = error  # raylint: allow(data-race) written before _done.set(); result() reads only after a successful wait
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Optional[str]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint save (step={self.step} rank={self.rank}) "
                f"still in flight after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._manifest_name


@dataclass
class _SaveJob:
    handle: SaveHandle
    skeleton_frame: bytes
    leaves: List[_LeafTask]
    step: int
    rank: int
    world_size: int
    shard_axis: Optional[int]
    shard_paths: Optional[Tuple[str, ...]]
    mesh: Optional[Dict[str, Any]]
    meta: Dict[str, Any]
    save_key: str
    # (trace_id, span_id) captured at save(): the writer thread adopts it
    # so hash/write/gather/commit child spans join the caller's trace
    trace: Tuple[str, str] = ("", "")


class CheckpointEngine:
    """Content-addressed checkpoint store rooted at a directory shared by
    every rank (local disk, NFS, or the spill dir)."""

    def __init__(self, root: str, *, num_to_keep: Optional[int] = None,
                 namespace: str = "default",
                 state_client: Optional[Any] = None):
        self.root = os.path.abspath(root)
        self.num_to_keep = num_to_keep
        self.namespace = namespace
        self._state_client = state_client
        mf.init_root(self.root)
        register_serve_root(self.root)
        self._queue: "queue.Queue[Optional[_SaveJob]]" = queue.Queue(
            maxsize=max(1, int(_config.checkpoint_queue_depth)))
        self._writer: Optional[threading.Thread] = None
        self._writer_lock = threading.Lock()
        self._inflight: List[SaveHandle] = []  # raylint: guarded-by(self._writer_lock)
        self._inflight_chunks: set = set()   # GC must not reap these
        self._closed = False
        self.stats = EngineStats()  # raylint: guarded-by(self._stats_lock)
        self._stats_lock = threading.Lock()  # io-pool workers share stats
        self._hash_cache = _HashCache()
        self._pool: Optional[ThreadPoolExecutor] = None

    # -- save -----------------------------------------------------------------

    def save(self, tree: Any, *, step: int, rank: int = 0,
             world_size: int = 1, shard_axis: Optional[int] = None,
             shard_paths: Optional[Any] = None,
             mesh: Optional[Dict[str, Any]] = None,
             meta: Optional[Dict[str, Any]] = None,
             save_key: Optional[str] = None,
             wait: bool = False,
             timeout_s: Optional[float] = None) -> SaveHandle:
        """Snapshot ``tree`` (this rank's shard of it). Returns once the
        device->host copy is enqueued; ``wait=True`` blocks through commit,
        raising ``TimeoutError`` if the commit outlives ``timeout_s``.

        ``shard_paths`` is required with ``shard_axis``: an iterable of
        fnmatch patterns over "/"-joined leaf paths naming exactly which
        leaves are split along the axis (``["params/*", "opt/mu/*"]``).
        Everything unmatched is treated as replicated — the engine never
        infers placement from shard contents.
        """
        if self._closed:
            raise CheckpointError("engine is closed")
        if (shard_axis is None) != (shard_paths is None):
            raise CheckpointError(
                "shard_axis and shard_paths must be passed together: the "
                "caller declares which leaves are axis-split (fnmatch "
                "patterns over '/'-joined paths); placement is never "
                "inferred from data")
        leaves: List[_LeafTask] = []
        skeleton = _extract_arrays(tree, (), leaves, self._make_leaf)
        handle = SaveHandle(step, rank)
        trace: Tuple[str, str] = ("", "")
        if observability.ENABLED:
            # checkpoint save is a trace entry point: join the caller's
            # trace when one is active, mint a fresh one otherwise
            trace = observability.current() or (observability.mint_id(), "")
        job = _SaveJob(
            handle=handle,
            skeleton_frame=bytes(dumps_framed(skeleton)),
            leaves=leaves, step=step, rank=rank, world_size=world_size,
            shard_axis=shard_axis,
            shard_paths=(None if shard_paths is None
                         else tuple(str(p) for p in shard_paths)),
            mesh=mesh, meta=dict(meta or {}),
            save_key=save_key or f"step-{step:08d}",
            trace=trace)
        self._ensure_writer()
        with self._writer_lock:
            self._inflight.append(handle)
        # Bounded-queue backpressure: when the writer falls behind, this
        # put blocks the training thread — goodput's ``ckpt_stall``.
        try:
            self._queue.put_nowait(job)  # raylint: allow(data-race) queue.Queue is internally synchronized
        except queue.Full:
            if goodput.ENABLED:
                with goodput.interval("ckpt_stall"):
                    self._queue.put(job)  # raylint: allow(data-race) queue.Queue is internally synchronized
            else:
                self._queue.put(job)  # raylint: allow(data-race) queue.Queue is internally synchronized
        if wait:
            if goodput.ENABLED:  # synchronous save: commit wait is a stall
                with goodput.interval("ckpt_stall"):
                    handle.result(timeout_s)
            else:
                handle.result(timeout_s)
        return handle

    def _make_leaf(self, path: str, value: Any) -> _LeafTask:
        """Caller-thread leaf builder: a hash-cache hit (plus a stat
        proving the chunk is still on disk — GC may have reaped it) skips
        the device->host copy entirely; everything else pays the copy now
        so the training step can proceed while the writer hashes."""
        hit = self._hash_cache.lookup(value)
        if hit is not None:
            chunk_id, nbytes, dtype, shape = hit
            if os.path.exists(os.path.join(self.root,
                                           mf.chunk_relpath(chunk_id))):
                return _LeafTask(path=path, nbytes=nbytes, dtype=dtype,
                                 shape=list(shape), chunk_id=chunk_id)
        arr = np.ascontiguousarray(np.asarray(value))
        return _LeafTask(path=path, nbytes=arr.nbytes, dtype=arr.dtype.str,
                         shape=list(arr.shape), arr=arr, origin=value)

    def _io_pool(self) -> Optional[ThreadPoolExecutor]:
        """Shared hash/write worker pool; None = serial path
        (``checkpoint_io_workers <= 1``)."""
        n = int(_config.checkpoint_io_workers)
        if n <= 1:
            return None
        with self._writer_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix="ckpt-io")
            return self._pool

    def _ensure_writer(self) -> None:
        with self._writer_lock:
            if self._writer is None or not self._writer.is_alive():
                self._writer = threading.Thread(
                    target=self._writer_loop,
                    name="ckpt-writer", daemon=True)
                self._writer.start()

    def _writer_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            try:
                name = self._process(job)
                job.handle._finish(name, None)
            except BaseException as e:
                logger.warning("checkpoint: save step=%d rank=%d failed: %s",
                               job.step, job.rank, e)
                job.handle._finish(None, e)
            finally:
                self._queue.task_done()
                with self._writer_lock:
                    try:
                        self._inflight.remove(job.handle)
                    except ValueError:
                        logger.debug("checkpoint: handle already reaped "
                                     "(flush raced the writer)")

    # -- the write path (writer thread) ---------------------------------------

    def _write_chunk(self, chunk_id: str, pieces: List, nbytes: int) -> None:
        if not perf.ENABLED:
            return self._write_chunk_impl(chunk_id, pieces, nbytes)
        t0 = time.monotonic()
        try:
            return self._write_chunk_impl(chunk_id, pieces, nbytes)
        finally:
            perf.observe("ckpt.write", (time.monotonic() - t0) * 1e3)

    def _write_chunk_impl(self, chunk_id: str, pieces: List,
                          nbytes: int) -> None:
        final = os.path.join(self.root, mf.chunk_relpath(chunk_id))
        if os.path.exists(final):
            with self._stats_lock:
                self.stats.chunks_deduped += 1
                self.stats.bytes_deduped += nbytes
            return
        os.makedirs(os.path.dirname(final), exist_ok=True)
        tmp = f"{final}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                for p in pieces:
                    f.write(p)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._stats_lock:
            self.stats.chunks_written += 1
            self.stats.chunk_bytes_written += nbytes

    def _process(self, job: _SaveJob) -> Optional[str]:
        # Writer thread: adopt the context captured at save() so the
        # stage spans below land in the submitting trace.
        token = (observability.set_current(*job.trace)
                 if observability.ENABLED and job.trace[0] else None)
        t0 = time.monotonic() if perf.ENABLED else 0.0
        try:
            with observability.span("checkpoint.save", cat="checkpoint",
                                    step=str(job.step), rank=str(job.rank)):
                return self._process_stages(job)
        finally:
            if t0:
                perf.observe("ckpt.save", (time.monotonic() - t0) * 1e3)
            if token is not None:
                observability.reset(token)

    def _leaf_chunk(self, leaf: _LeafTask, dropped: bool,
                    protected: List[str]) -> str:
        """Hash + write one leaf (io-pool worker or inline on the writer
        thread). Returns the chunk id."""
        if leaf.chunk_id is not None:
            # hash-cache hit: the chunk was stat-proven present at save()
            # time — account the dedup without touching the bytes (no
            # host copy, no hash, no write)
            protected.append(leaf.chunk_id)
            self._inflight_chunks.add(leaf.chunk_id)  # raylint: allow(data-race) GIL-atomic set add; worst case protects a chunk from cleanup twice
            with self._stats_lock:
                self.stats.chunks_deduped += 1
                self.stats.bytes_deduped += leaf.nbytes
            return leaf.chunk_id
        t0 = time.monotonic() if perf.ENABLED else 0.0
        with observability.span("checkpoint.hash", cat="checkpoint",
                                path=leaf.path):
            chunk_id = _hash_array(leaf.arr)
        if t0:
            perf.observe("ckpt.hash", (time.monotonic() - t0) * 1e3)
        protected.append(chunk_id)
        self._inflight_chunks.add(chunk_id)  # raylint: allow(data-race) GIL-atomic set add; worst case protects a chunk from cleanup twice
        if leaf.origin is not None:
            self._hash_cache.remember(leaf.origin, chunk_id, leaf.nbytes,
                                      leaf.dtype, leaf.shape)
        if not dropped:
            payload = FramedPayload(leaf.arr)
            with observability.span("checkpoint.write",
                                    cat="checkpoint", path=leaf.path):
                self._write_chunk(chunk_id, payload.pieces, leaf.nbytes)
        return chunk_id

    def _process_stages(self, job: _SaveJob) -> Optional[str]:
        with self._stats_lock:
            self.stats.saves += 1
        protected: List[str] = []
        try:
            pool = self._io_pool()
            # Chaos choke points fire here, on the writer thread in leaf
            # submission order — a schedule's nth checkpoint.write firing
            # hits the same leaf with or without the worker pool.
            results: List[Any] = []
            for leaf in job.leaves:
                dropped = False
                if chaos.ENABLED:
                    dropped = chaos.inject(
                        "checkpoint.write", path=leaf.path,
                        rank=str(job.rank)) == "drop"
                if pool is None:
                    results.append(self._leaf_chunk(leaf, dropped, protected))
                else:
                    results.append(pool.submit(
                        self._leaf_chunk, leaf, dropped, protected))
            if pool is not None:
                chunk_ids, errors = [], []
                for fut in results:
                    try:
                        chunk_ids.append(fut.result())
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                        chunk_ids.append(None)
                if errors:
                    raise errors[0]
            else:
                chunk_ids = results
            entries = [
                ArrayEntry(
                    path=leaf.path, slot=slot, chunk=cid, nbytes=leaf.nbytes,
                    dtype=leaf.dtype, shape=list(leaf.shape),
                    sharded=(job.shard_paths is not None and any(
                        fnmatch.fnmatchcase(leaf.path, pat)
                        for pat in job.shard_paths)))
                # a dropped (lost) write still indexes the chunk: the
                # committer's presence check then fails the save loudly
                # instead of publishing a manifest missing the array
                for slot, (leaf, cid) in enumerate(zip(job.leaves,
                                                       chunk_ids))]
            skel_id = mf.hash_bytes("skeleton", job.skeleton_frame)
            protected.append(skel_id)
            self._inflight_chunks.add(skel_id)  # raylint: allow(data-race) GIL-atomic set add; worst case protects a chunk from cleanup twice
            if chaos.ENABLED:
                chaos.inject("checkpoint.write", path="<skeleton>",
                             rank=str(job.rank))
            with observability.span("checkpoint.write", cat="checkpoint",
                                    path="<skeleton>"):
                self._write_chunk(skel_id, [job.skeleton_frame],
                                  len(job.skeleton_frame))
            shard = ShardIndex(rank=job.rank, skeleton=skel_id,
                               skeleton_nbytes=len(job.skeleton_frame),
                               arrays=entries)
            pend_dir = os.path.join(self.root, mf.PENDING_DIR, job.save_key)
            os.makedirs(pend_dir, exist_ok=True)
            # fsync=False: the pending index only matters to a commit in
            # THIS boot — a crash abandons the save either way, and the
            # manifest/LATEST writes that make it durable still fsync
            mf.atomic_write_bytes(
                os.path.join(pend_dir, f"shard-{job.rank}.json"),
                json.dumps({"step": job.step, "world_size": job.world_size,
                            "shard": shard.to_json()}).encode(),
                fsync=False)
            if job.rank != 0:
                return None
            return self._commit(job, pend_dir)
        finally:
            # raylint: allow(data-race) GIL-atomic set op; a racing saver re-adds its chunk before the next GC scan
            self._inflight_chunks.difference_update(
                [c for c in protected if c])

    def _commit(self, job: _SaveJob, pend_dir: str) -> str:
        with observability.span("checkpoint.gather", cat="checkpoint",
                                step=str(job.step),
                                world_size=str(job.world_size)):
            shards = self._gather_shards(job, pend_dir)
        if job.shard_axis is not None:
            _finalize_sharding(shards, job.shard_axis)
        m = Manifest(id=mf.new_manifest_id(), step=job.step,
                     world_size=job.world_size, shards=shards,
                     shard_axis=job.shard_axis, mesh=job.mesh, meta=job.meta)
        if not mf.chunks_present(self.root, m):
            raise CheckpointError(
                f"step {job.step}: chunk(s) missing at commit time "
                "(lost or dropped write) — refusing to publish a torn "
                "manifest")
        t0 = time.monotonic() if perf.ENABLED else 0.0
        with observability.span("checkpoint.commit", cat="checkpoint",
                                step=str(job.step)):
            if chaos.ENABLED:
                chaos.inject("checkpoint.commit", stage="manifest",
                             step=str(job.step))
            name = mf.write_manifest(self.root, m)
            if chaos.ENABLED:
                chaos.inject("checkpoint.commit", stage="latest",
                             step=str(job.step))
            mf.set_latest(self.root, name)
        if t0:
            perf.observe("ckpt.commit", (time.monotonic() - t0) * 1e3)
        with self._stats_lock:
            self.stats.commits += 1
        self._register(name)
        self._cleanup_pending(pend_dir)
        if self.num_to_keep is not None:
            self._prune(self.num_to_keep)
        return name

    def _gather_shards(self, job: _SaveJob, pend_dir: str) -> List[ShardIndex]:
        """Rank 0 waits for every rank's shard index in pending/."""
        deadline = time.monotonic() + float(_config.checkpoint_shard_wait_s)
        want = {r: os.path.join(pend_dir, f"shard-{r}.json")
                for r in range(job.world_size)}
        shards: Dict[int, ShardIndex] = {}
        while True:
            for r, path in list(want.items()):
                try:
                    with open(path, encoding="utf-8") as f:
                        d = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                if d.get("step") != job.step:
                    continue  # stale file from a crashed earlier attempt
                shards[r] = ShardIndex.from_json(d["shard"])
                del want[r]
            if not want:
                return [shards[r] for r in sorted(shards)]
            if time.monotonic() >= deadline:
                raise CheckpointError(
                    f"step {job.step}: ranks {sorted(want)} never delivered "
                    f"shard indexes within "
                    f"{_config.checkpoint_shard_wait_s}s — save abandoned "
                    "(previous checkpoint remains the restore point)")
            time.sleep(0.005)  # raylint: allow(bare-retry) local-FS poll under the explicit checkpoint_shard_wait_s deadline above

    def _register(self, name: str) -> None:
        client = self._state_client
        if client is None:
            return
        try:
            client.kv_put(f"ckpt/{self.namespace}/latest".encode(),
                          name.encode())
        except Exception as e:
            # registration is advisory (LATEST on disk is authoritative);
            # a dead state service must not fail a durable commit
            logger.debug("checkpoint: state-service register failed: %s", e)

    def _cleanup_pending(self, pend_dir: str) -> None:
        try:
            for fn in os.listdir(pend_dir):
                os.unlink(os.path.join(pend_dir, fn))
            os.rmdir(pend_dir)
        except OSError as e:
            logger.debug("checkpoint: pending cleanup left residue: %s", e)

    # -- retention / GC -------------------------------------------------------

    def _prune(self, keep: int) -> None:
        # Retention keeps the most recently COMMITTED manifests (file
        # mtime), not the highest step numbers: a step counter that
        # restarted after a crash writes fresh low-step manifests which
        # must out-live stale pre-crash high-step ones.
        names = mf.list_manifest_names_by_commit_time(self.root)
        for name in names[:-keep] if keep > 0 else names:
            try:
                os.unlink(os.path.join(self.root, mf.MANIFESTS_DIR, name))
            except OSError as e:
                logger.debug("checkpoint: prune of %s failed: %s", name, e)
        self.gc()

    def gc(self) -> int:
        """Reap chunk files no committed manifest references (crashed saves
        leave orphans by design).

        Every rank runs its own engine on the same shared root, so "live"
        must be judged cross-process, not from this instance alone: chunks
        named by any ``pending/`` shard index belong to a save some
        committer may still publish, and any file younger than
        ``checkpoint_gc_grace_s`` is left alone — a peer's freshly written
        chunk may precede its shard index, and unlinking a peer's tmp file
        would fail its imminent ``os.replace``.
        """
        referenced = set(self._inflight_chunks)
        for name in mf.list_manifest_names(self.root):
            try:
                referenced.update(mf.read_manifest(self.root, name)
                                  .chunk_ids())
            except CheckpointError:
                logger.warning("checkpoint: gc skipping unreadable manifest "
                               "%s (its chunks stay protected-by-absence)",
                               name)
                return 0  # cannot prove anything is orphaned
        grace = max(0.0, float(_config.checkpoint_gc_grace_s))
        # stale pending indexes (older than the committer's shard-wait
        # deadline plus grace) can never join a commit — ignore them so a
        # crashed attempt's residue doesn't pin chunks forever
        referenced.update(mf.pending_chunk_ids(
            self.root,
            max_age_s=float(_config.checkpoint_shard_wait_s) + grace))
        now = time.time()
        reaped = 0
        chunks_dir = os.path.join(self.root, mf.CHUNKS_DIR)
        for sub in os.listdir(chunks_dir):
            subdir = os.path.join(chunks_dir, sub)
            if not os.path.isdir(subdir):
                continue
            for fn in os.listdir(subdir):
                if fn.split(".tmp-")[0] in referenced and ".tmp-" not in fn:
                    continue
                path = os.path.join(subdir, fn)
                try:
                    if grace and now - os.path.getmtime(path) < grace:
                        continue
                    os.unlink(path)
                    reaped += 1
                except OSError as e:
                    logger.debug("checkpoint: gc skipped %s: %s", fn, e)
        with self._stats_lock:
            self.stats.chunks_gced += reaped
        return reaped

    # -- restore --------------------------------------------------------------

    def latest(self) -> Optional[str]:
        return mf.resolve_latest(self.root)

    def restore(self, manifest_name: Optional[str] = None, *, rank: int = 0,
                world_size: int = 1,
                fetch_from: Optional["ChunkFetcher"] = None) -> Any:
        return load(self.root, manifest_name, rank=rank,
                    world_size=world_size, fetch_from=fetch_from)

    # -- lifecycle ------------------------------------------------------------

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait for every in-flight save. True when all completed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._writer_lock:
                pending = list(self._inflight)
            if not pending:
                return True
            for h in pending:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                if not h.wait(left):
                    return False

    def close(self, timeout: Optional[float] = None) -> None:
        if self._closed:
            return
        self.flush(timeout)
        self._closed = True
        with self._writer_lock:
            writer = self._writer
            pool = self._pool
        if writer is not None and writer.is_alive():
            self._queue.put(None)  # raylint: allow(data-race) queue.Queue is internally synchronized
            writer.join(timeout=5.0)
        if pool is not None:
            pool.shutdown(wait=True)


# -- engine-less read path ----------------------------------------------------

#: ``fetch_from`` contract: ``(chunk_id) -> Optional[bytes]`` — the
#: distributed runtime's striped remote chunk fetch, or any callable that
#: can produce a missing chunk's bytes. None return = not found there
#: either.
ChunkFetcher = Callable[[str], Optional[bytes]]


def _read_chunk(root: str, chunk_id: str,
                fetch_from: Optional[ChunkFetcher] = None) -> bytes:
    path = os.path.join(root, mf.chunk_relpath(chunk_id))
    try:
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        if fetch_from is None:
            raise CheckpointCorruption(
                f"chunk {chunk_id[:12]}… missing at {root}")
    try:
        data = fetch_from(chunk_id)
    except Exception as e:
        raise CheckpointCorruption(
            f"chunk {chunk_id[:12]}… missing at {root} and the remote "
            f"fetch failed: {e}")
    if data is None:
        raise CheckpointCorruption(
            f"chunk {chunk_id[:12]}… missing at {root} and at the remote "
            "peer")
    # Write-through: later entries (and later restores) find the chunk
    # locally. Content-addressed + hash-verified on load, so no fsync —
    # a torn write is caught and refetched, never trusted.
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        mf.atomic_write_bytes(path, data, fsync=False)
    except OSError as e:
        logger.debug("checkpoint: chunk write-through failed: %s", e)
    return data


def _load_array(root: str, e: ArrayEntry, verify: bool,
                fetch_from: Optional[ChunkFetcher] = None) -> np.ndarray:
    value, _ = loads_framed(_read_chunk(root, e.chunk, fetch_from))
    arr = np.asarray(value)
    if verify:
        got = _hash_array(np.ascontiguousarray(arr))
        if got != e.chunk:
            raise CheckpointCorruption(
                f"chunk for {e.path!r} failed hash verification "
                f"(manifest {e.chunk[:12]}…, disk {got[:12]}…)")
    return arr


def _load_slots(root: str, entries: List[ArrayEntry], verify: bool,
                fetch_from: Optional[ChunkFetcher]) -> Dict[int, np.ndarray]:
    """Concurrent chunk reads (``checkpoint_io_workers``): restore is
    read+hash per leaf, which overlaps the same way the save path does."""
    workers = min(int(_config.checkpoint_io_workers), len(entries))
    if workers <= 1:
        return {e.slot: _load_array(root, e, verify, fetch_from)
                for e in entries}
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="ckpt-read") as ex:
        futs = [(e.slot, ex.submit(_load_array, root, e, verify, fetch_from))
                for e in entries]
        return {slot: f.result() for slot, f in futs}


def _load_shard(root: str, shard: ShardIndex, verify: bool,
                fetch_from: Optional[ChunkFetcher] = None) -> Any:
    skeleton, _ = loads_framed(_read_chunk(root, shard.skeleton, fetch_from))
    slots = _load_slots(root, shard.arrays, verify, fetch_from)
    return _inject_arrays(skeleton, slots)


def _finalize_sharding(shards: List[ShardIndex], axis: int) -> None:
    """Stamp global_shape/offset onto the leaves the ranks DECLARED split
    along ``axis`` (``save(shard_paths=...)`` → ``ArrayEntry.sharded``).
    Undeclared leaves — scalars, replicated params, per-rank-distinct RNG
    keys — restore replicated; a declared leaf whose shards don't actually
    assemble (missing on a rank, inconsistent flags, axis out of range,
    mismatched non-axis dims) fails the commit loudly rather than
    publishing a manifest that reshards into garbage."""
    by_path: Dict[str, List[ArrayEntry]] = {}
    for s in shards:
        for e in s.arrays:
            by_path.setdefault(e.path, []).append(e)
    nranks = len(shards)
    for path, entries in by_path.items():
        marked = sum(1 for e in entries if e.sharded)
        if marked == 0:
            continue
        if marked != len(entries) or len(entries) != nranks:
            raise CheckpointError(
                f"leaf {path!r} is declared axis-split on {marked} of "
                f"{len(entries)} entries across {nranks} ranks — every "
                "rank must save it with a matching shard_paths pattern")
        shapes = [e.shape for e in entries]
        if any(len(sh) <= axis for sh in shapes):
            raise CheckpointError(
                f"leaf {path!r} is declared split along axis {axis} but "
                f"has shape(s) {shapes} without that axis")
        base = shapes[0][:axis] + shapes[0][axis + 1:]
        if any(sh[:axis] + sh[axis + 1:] != base for sh in shapes[1:]):
            raise CheckpointError(
                f"leaf {path!r} is declared split along axis {axis} but "
                f"non-axis dims differ across ranks: {shapes}")
        total = sum(sh[axis] for sh in shapes)
        off = 0
        for e in entries:   # shards arrive rank-sorted from the committer
            g = list(e.shape)
            g[axis] = total
            o = [0] * len(g)
            o[axis] = off
            e.global_shape, e.offset = g, o
            off += e.shape[axis]


def _load_resharded(root: str, m: Manifest, rank: int, world_size: int,
                    verify: bool,
                    fetch_from: Optional[ChunkFetcher] = None) -> Any:
    """World size changed on an axis-sharded save: rebuild each global
    array from recorded offsets, then take this rank's equal split. One
    worker per leaf (each assembles its shard parts serially into the
    global buffer) keeps reads+hashing concurrent without two workers
    racing on one destination array."""
    axis = m.shard_axis
    assert axis is not None
    skeleton, _ = loads_framed(_read_chunk(root, m.shards[0].skeleton,
                                           fetch_from))

    def _load_leaf(e0: ArrayEntry) -> np.ndarray:
        if e0.global_shape is None:
            return _load_array(root, e0, verify, fetch_from)
        glob = np.empty(tuple(e0.global_shape), dtype=np.dtype(e0.dtype))
        for s in m.shards:
            e = next(x for x in s.arrays if x.path == e0.path)
            part = _load_array(root, e, verify, fetch_from)
            sel = [slice(None)] * glob.ndim
            sel[axis] = slice(e.offset[axis], e.offset[axis] + e.shape[axis])
            glob[tuple(sel)] = part.reshape(tuple(e.shape))
        dim = glob.shape[axis]
        lo, hi = rank * dim // world_size, (rank + 1) * dim // world_size
        sel = [slice(None)] * glob.ndim
        sel[axis] = slice(lo, hi)
        return glob[tuple(sel)]

    entries = m.shards[0].arrays
    workers = min(int(_config.checkpoint_io_workers), len(entries))
    if workers <= 1:
        slots = {e0.slot: _load_leaf(e0) for e0 in entries}
    else:
        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="ckpt-read") as ex:
            futs = [(e0.slot, ex.submit(_load_leaf, e0)) for e0 in entries]
            slots = {slot: f.result() for slot, f in futs}
    return _inject_arrays(skeleton, slots)


def load(root: str, manifest_name: Optional[str] = None, *, rank: int = 0,
         world_size: int = 1,
         fetch_from: Optional[ChunkFetcher] = None) -> Any:
    """Restore one rank's view of a committed checkpoint (thread-free read
    path; the engine's ``restore`` delegates here). ``fetch_from`` pulls
    chunks missing under ``root`` from a remote peer (the distributed
    runtime's striped transport fetch) and caches them write-through."""
    root = os.path.abspath(root)
    if manifest_name is None:
        manifest_name = mf.resolve_latest(root)
        if manifest_name is None:
            raise CheckpointNotFound(f"no committed checkpoint under {root}")
    m = mf.read_manifest(root, manifest_name)
    if chaos.ENABLED:
        chaos.inject("checkpoint.restore", manifest=manifest_name,
                     rank=str(rank))
    verify = bool(_config.checkpoint_hash_verify)
    if m.shard_axis is None:
        # replicated: every shard is a full tree; any one serves any rank
        return _load_shard(root, m.shards[rank % len(m.shards)], verify,
                           fetch_from)
    if world_size == m.world_size:
        by_rank = {s.rank: s for s in m.shards}
        return _load_shard(root, by_rank[rank], verify, fetch_from)
    return _load_resharded(root, m, rank, world_size, verify, fetch_from)


@dataclass
class CheckpointRef:
    """Picklable pointer to a committed checkpoint — what trials, results
    and serve configs carry instead of directory copies or value blobs."""

    root: str
    manifest_name: Optional[str] = None   # None = latest at load time

    def load(self, rank: int = 0, world_size: int = 1,
             fetch_from: Optional[ChunkFetcher] = None) -> Any:
        return load(self.root, self.manifest_name, rank=rank,
                    world_size=world_size, fetch_from=fetch_from)

    def exists(self) -> bool:
        try:
            name = self.manifest_name or mf.resolve_latest(self.root)
            return name is not None and mf.chunks_present(
                self.root, mf.read_manifest(self.root, name))
        except CheckpointError:
            return False
