"""Checkpoint manifest format + crash-atomic commit primitives.

A checkpoint on disk is a content-addressed chunk store plus a small JSON
*manifest* naming the chunks. Layout under an engine root::

    chunks/<aa>/<sha256>          immutable content-addressed chunk files
    manifests/ck-<step>-<uid>.json   one manifest per committed checkpoint
    pending/<save-key>/shard-<rank>.json   per-rank shard indexes awaiting
                                           the committer (removed on commit)
    LATEST                        name of the newest committed manifest

Durability contract (the reason restore can never see a torn checkpoint):

1. chunk files land under a temp name and are ``os.replace``d into their
   hash name — a chunk either has its final name and is complete, or it
   does not exist;
2. the manifest is written tmp + fsync + ``os.replace`` — same property;
3. ``LATEST`` is updated (tmp + replace) only *after* the manifest rename.

A crash between (2) and (3) leaves ``LATEST`` on the predecessor while the
new manifest is already fully readable; a crash anywhere earlier leaves at
worst orphaned chunks/tmp files, which refcount GC reaps. Restore resolves
``LATEST`` first and falls back to scanning ``manifests/`` for the newest
manifest whose chunks all exist.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

FORMAT = "rtck/1"

CHUNKS_DIR = "chunks"
MANIFESTS_DIR = "manifests"
PENDING_DIR = "pending"
LATEST_FILE = "LATEST"


class CheckpointError(RuntimeError):
    """Base error for the checkpoint engine."""


class CheckpointCorruption(CheckpointError):
    """A chunk failed hash verification or a manifest references missing
    chunks — the checkpoint must not be trusted."""


class CheckpointNotFound(CheckpointError):
    """No committed manifest exists (yet) at the given root."""


# -- atomic file primitives ---------------------------------------------------

def atomic_write_bytes(path: str, data: bytes, *, fsync: bool = True) -> None:
    """tmp-in-same-dir + fsync + rename: ``path`` is either absent/old or
    complete — never partial. ``fsync=False`` keeps the rename atomicity
    (no torn file visible to readers) but skips the durability barrier —
    for files that only matter within this boot (pending shard indexes,
    write-through chunk caches)."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tmp-", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def chunk_relpath(chunk_id: str) -> str:
    return os.path.join(CHUNKS_DIR, chunk_id[:2], chunk_id)


def hash_bytes(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, str):
            p = p.encode()
        h.update(p)
    return h.hexdigest()


# -- manifest schema ----------------------------------------------------------

@dataclass
class ArrayEntry:
    """One array leaf of one shard: a content-addressed chunk plus enough
    metadata to verify it and to place it inside the global array when the
    save was sharded."""

    path: str                 # "/"-joined key path inside the pytree
    slot: int                 # position in the shard's array-slot ordering
    chunk: str                # sha256 content hash (= data identity)
    nbytes: int
    dtype: str
    shape: List[int]
    # Declared by the caller at save time (save(shard_paths=...)); the
    # committer trusts this flag — placement is never inferred from data.
    sharded: bool = False
    global_shape: Optional[List[int]] = None   # set when sharded
    offset: Optional[List[int]] = None         # per-dim start inside global

    def to_json(self) -> Dict[str, Any]:
        d = {"path": self.path, "slot": self.slot, "chunk": self.chunk,
             "nbytes": self.nbytes, "dtype": self.dtype,
             "shape": list(self.shape)}
        if self.sharded:
            d["sharded"] = True
        if self.global_shape is not None:
            d["global_shape"] = list(self.global_shape)
            d["offset"] = list(self.offset or [0] * len(self.global_shape))
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "ArrayEntry":
        return cls(path=d["path"], slot=d["slot"], chunk=d["chunk"],
                   nbytes=d["nbytes"], dtype=d["dtype"],
                   shape=list(d["shape"]),
                   sharded=bool(d.get("sharded", False)),
                   global_shape=d.get("global_shape"),
                   offset=d.get("offset"))


@dataclass
class ShardIndex:
    """What one rank wrote: the skeleton chunk (treedef + non-array leaves,
    array leaves replaced by slot markers) and one entry per array leaf."""

    rank: int
    skeleton: str             # chunk id of the pickled skeleton
    skeleton_nbytes: int
    arrays: List[ArrayEntry] = field(default_factory=list)

    def chunk_ids(self) -> List[str]:
        return [self.skeleton] + [a.chunk for a in self.arrays]

    def to_json(self) -> Dict[str, Any]:
        return {"rank": self.rank, "skeleton": self.skeleton,
                "skeleton_nbytes": self.skeleton_nbytes,
                "arrays": [a.to_json() for a in self.arrays]}

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "ShardIndex":
        return cls(rank=d["rank"], skeleton=d["skeleton"],
                   skeleton_nbytes=d["skeleton_nbytes"],
                   arrays=[ArrayEntry.from_json(a) for a in d["arrays"]])


@dataclass
class Manifest:
    """The commit unit: a save is durable iff its manifest file exists."""

    id: str
    step: int
    world_size: int
    shards: List[ShardIndex]
    shard_axis: Optional[int] = None      # None = each shard is a full tree
    mesh: Optional[Dict[str, Any]] = None
    meta: Dict[str, Any] = field(default_factory=dict)
    created: float = 0.0
    format: str = FORMAT

    @property
    def filename(self) -> str:
        return f"ck-{self.step:08d}-{self.id}.json"

    def chunk_ids(self) -> List[str]:
        out: List[str] = []
        for s in self.shards:
            out.extend(s.chunk_ids())
        return out

    def total_bytes(self) -> int:
        return sum(s.skeleton_nbytes + sum(a.nbytes for a in s.arrays)
                   for s in self.shards)

    def to_json(self) -> Dict[str, Any]:
        return {"format": self.format, "id": self.id, "step": self.step,
                "created": self.created, "world_size": self.world_size,
                "shard_axis": self.shard_axis, "mesh": self.mesh,
                "meta": self.meta,
                "shards": [s.to_json() for s in self.shards]}

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Manifest":
        if d.get("format") != FORMAT:
            raise CheckpointCorruption(
                f"unknown manifest format {d.get('format')!r} "
                f"(engine speaks {FORMAT})")
        return cls(id=d["id"], step=d["step"], world_size=d["world_size"],
                   shards=[ShardIndex.from_json(s) for s in d["shards"]],
                   shard_axis=d.get("shard_axis"), mesh=d.get("mesh"),
                   meta=d.get("meta") or {}, created=d.get("created", 0.0))


def new_manifest_id() -> str:
    return uuid.uuid4().hex[:8]


# -- root-level operations ----------------------------------------------------

def init_root(root: str) -> None:
    for sub in (CHUNKS_DIR, MANIFESTS_DIR, PENDING_DIR):
        os.makedirs(os.path.join(root, sub), exist_ok=True)


def write_manifest(root: str, m: Manifest) -> str:
    """Atomically publish ``m`` (step 2 of the commit protocol). Returns
    the manifest filename. The caller advances LATEST separately."""
    if not m.created:
        m.created = time.time()
    path = os.path.join(root, MANIFESTS_DIR, m.filename)
    atomic_write_bytes(path, json.dumps(m.to_json(), indent=1).encode())
    return m.filename


def set_latest(root: str, manifest_name: str) -> None:
    atomic_write_bytes(os.path.join(root, LATEST_FILE),
                       manifest_name.encode())


def read_manifest(root: str, manifest_name: str) -> Manifest:
    path = os.path.join(root, MANIFESTS_DIR, manifest_name)
    try:
        with open(path, encoding="utf-8") as f:
            return Manifest.from_json(json.load(f))
    except FileNotFoundError:
        raise CheckpointNotFound(f"no manifest {manifest_name!r} at {root}")
    except (json.JSONDecodeError, KeyError) as e:
        raise CheckpointCorruption(f"manifest {manifest_name!r} unreadable: "
                                   f"{e}") from e


def list_manifest_names(root: str) -> List[str]:
    d = os.path.join(root, MANIFESTS_DIR)
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return []
    return sorted(n for n in names
                  if n.startswith("ck-") and n.endswith(".json"))


def list_manifest_names_by_commit_time(root: str) -> List[str]:
    """Manifest names oldest-commit-first (file mtime, name tie-break).

    Retention and the LATEST fallback scan order by *commit recency*, not
    by the step embedded in the filename: a caller whose step counter
    restarted (a new engine attempt after a crash) must never have its
    fresh commits out-sorted — and reaped — by stale higher-step
    manifests from before the crash.
    """
    def mtime(name: str) -> float:
        try:
            return os.path.getmtime(os.path.join(root, MANIFESTS_DIR, name))
        except OSError:
            return 0.0
    return sorted(list_manifest_names(root), key=lambda n: (mtime(n), n))


def pending_chunk_ids(root: str,
                      max_age_s: Optional[float] = None) -> set:
    """Chunk ids referenced by any rank's pending/ shard index — an
    in-flight save that some committer may still publish. GC must treat
    these as live even though no committed manifest names them yet.
    Indexes older than ``max_age_s`` are ignored: the committer's
    shard-wait deadline has long expired, so they can never join a commit
    (crashed attempts must not protect their residue forever)."""
    out: set = set()
    pend = os.path.join(root, PENDING_DIR)
    try:
        keys = os.listdir(pend)
    except OSError:
        return out
    now = time.time()
    for key in keys:
        d = os.path.join(pend, key)
        try:
            files = os.listdir(d)
        except OSError:
            continue
        for fn in files:
            if not (fn.startswith("shard-") and fn.endswith(".json")):
                continue
            path = os.path.join(d, fn)
            try:
                if max_age_s is not None \
                        and now - os.path.getmtime(path) > max_age_s:
                    continue
                with open(path, encoding="utf-8") as f:
                    out.update(ShardIndex.from_json(
                        json.load(f)["shard"]).chunk_ids())
            except (OSError, json.JSONDecodeError, KeyError, TypeError):
                continue  # torn/stale index protects nothing
    return out


def chunks_present(root: str, m: Manifest) -> bool:
    return all(os.path.exists(os.path.join(root, chunk_relpath(c)))
               for c in m.chunk_ids())


def resolve_latest(root: str) -> Optional[str]:
    """Name of the newest *complete* committed manifest, or None.

    Trusts ``LATEST`` when it points at a manifest whose chunks all exist
    (the normal case); otherwise scans ``manifests/`` newest-commit-first
    and returns the first fully-present one — this is what makes a crash
    between manifest rename and LATEST update harmless.
    """
    try:
        with open(os.path.join(root, LATEST_FILE), encoding="utf-8") as f:
            name = f.read().strip()
    except OSError:
        name = ""
    if name:
        try:
            if chunks_present(root, read_manifest(root, name)):
                return name
        except CheckpointError:
            pass
    for name in reversed(list_manifest_names_by_commit_time(root)):
        try:
            if chunks_present(root, read_manifest(root, name)):
                return name
        except CheckpointError:
            continue
    return None
