"""Developer tooling: framework-aware static analysis + runtime watchdogs.

Two enforcement layers for the concurrency invariants the runtime's design
depends on (threads-as-workers inside a single device-owner daemon, see
``_private/distributed.py``):

- :mod:`ray_tpu.devtools.linter` — an AST lint engine with rules that know
  about this framework's idioms (blocking calls in async bodies, lock-order
  consistency, unguarded cross-thread state, silent exception swallows,
  host-device syncs reachable from jitted step loops, proto/pb2 drift).
  CLI: ``python -m ray_tpu.devtools.lint ray_tpu``; ``--rules`` with no
  value prints the machine-readable registry.
- :mod:`ray_tpu.devtools.callgraph` — the whole-program symbol table +
  call graph (import/alias resolution, ``self.method`` and attribute-type
  inference, spawn/loop/call edge kinds) behind the interprocedural rules:
  R10 transitive async blocking, R11 cross-function lock-order cycles,
  R12 SPMD collective divergence, R13 config-knob / chaos-point drift.
  Unresolvable dynamic calls degrade to "unknown" edges — the analysis
  under-approximates rather than risk false positives.
- :mod:`ray_tpu.devtools.lockwatch` — a runtime lock-order watchdog that
  wraps ``threading.Lock``/``RLock`` creation, builds the cross-thread
  lock-order graph actually exercised, and reports cycles (potential
  deadlocks) and over-threshold holds.  Activated by ``RAY_TPU_LOCKWATCH=1``
  so any test run doubles as its workload; its cycle report format is
  shared with R11 so static and runtime findings correlate one-to-one.
"""

from ray_tpu.devtools.linter import LintEngine, Finding  # noqa: F401
