"""Developer tooling: framework-aware static analysis + runtime watchdogs.

Two enforcement layers for the concurrency invariants the runtime's design
depends on (threads-as-workers inside a single device-owner daemon, see
``_private/distributed.py``):

- :mod:`ray_tpu.devtools.linter` — an AST lint engine with rules that know
  about this framework's idioms (blocking calls in async bodies, lock-order
  consistency, unguarded cross-thread state, silent exception swallows,
  host-device syncs reachable from jitted step loops, proto/pb2 drift).
  CLI: ``python -m ray_tpu.devtools.lint ray_tpu``.
- :mod:`ray_tpu.devtools.lockwatch` — a runtime lock-order watchdog that
  wraps ``threading.Lock``/``RLock`` creation, builds the cross-thread
  lock-order graph actually exercised, and reports cycles (potential
  deadlocks) and over-threshold holds.  Activated by ``RAY_TPU_LOCKWATCH=1``
  so any test run doubles as its workload.
"""

from ray_tpu.devtools.linter import LintEngine, Finding  # noqa: F401
