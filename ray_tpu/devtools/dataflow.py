"""Flow- and path-sensitive dataflow facts over the callgraph ProjectIndex.

This module is the analysis layer under the R16-R18 rule families in
:mod:`ray_tpu.devtools.linter`:

- **resource lifecycle** (R16): a path-sensitive abstract interpreter
  walks each function body tracking *acquire/release facts* for OS-backed
  resources (sockets, file handles, mmaps, non-daemon threads, executor
  pools).  Each explicit path to a function exit — fall-through,
  ``return``, ``raise``, or an exception edge modeled through
  ``try``/``except``/``finally`` — must end with every tracked resource
  released or its ownership transferred.
- **deadline propagation** (R17): per-function *naked-blocking facts*
  (``.wait()`` / ``.join()`` / ``.result()`` / lock ``.acquire()`` with no
  timeout) are closed over the interprocedural call graph and intersected
  with *deadline-scoped entry points* (functions carrying a
  ``deadline``/``timeout``/``budget`` parameter or arming a
  ``BackoffPolicy`` budget).
- **protocol conformance** (R18): *send facts* (``pb.<METHOD>`` handed to
  an RPC send primitive) and *handle facts* (``.method`` compared against
  ``pb.<METHOD>``, plus ``case raytpu::<METHOD>`` dispatch in the native
  state service) are cross-checked, reply discipline is verified along
  every handler path, and node-lifecycle state writes are checked against
  the declared ``NODE_LIFECYCLE`` transition table.

The fact lattice per tracked resource is the four-point powerset of
``{released, escaped}``; a resource is *live* while neither bit is set,
and only live-at-exit facts become findings.  The stance matches the
callgraph layer's under-approximation contract: anything the walker
cannot prove it understands (dynamic calls, ``yield``-suspended frames,
a name captured by a nested def, a value stored into a container or
handed to an unresolved callee) degrades to "ownership left this
function" — which can only *suppress* findings, never invent one.
Implicit mid-function exceptions are not modeled either, with two
deliberate exceptions: inside a ``try`` body an exception may strike
after any statement prefix (that is what the handler edges are for), and
inside ``__init__`` any call may abort construction (a constructor that
raises strands every resource its half-built instance owns).

Ownership transfer into pools/rings/registries is recognized
structurally (stores, container adds, resolved callees that keep their
argument) and can be asserted explicitly where the sink is dynamic::

    sock = socket.create_connection(addr)  # raylint: transfer(socket) conn thread owns it
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Resource", "ExitState", "FunctionDataflow", "resource_leaks",
    "naked_blocking", "deadline_params", "arms_backoff_budget",
    "protocol_sends", "protocol_handlers", "native_protocol_facts",
    "proto_method_names", "reply_candidates", "lifecycle_writes",
    "NODE_LIFECYCLE", "module_global_names", "guarded_decls",
    "atomic_attr_keys", "ATOMIC_TYPE_LEAVES",
]

_TRANSFER_RE = re.compile(r"#\s*raylint:\s*transfer\(([A-Za-z0-9_,\- ]+)\)")


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _resolved_dotted(node: ast.AST, ctx) -> Optional[str]:
    """Dotted name with the head segment resolved through the file's
    imports (``import socket as _socket`` makes ``_socket.socket`` read
    as ``socket.socket``; ``from concurrent.futures import
    ThreadPoolExecutor`` resolves the bare name to its origin)."""
    raw = _dotted(node)
    if not raw:
        return None
    head, _, rest = raw.partition(".")
    origin = ctx.import_origin.get(head)
    if origin:
        return origin + ("." + rest if rest else "")
    return raw


# --------------------------------------------------------------------------
# resource-lifecycle facts (R16)

# resolved constructor dotted name -> resource kind
ACQUIRE_TABLE: Dict[str, str] = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "socket.socketpair": "socket",
    "open": "file",
    "io.open": "file",
    "os.fdopen": "file",
    "gzip.open": "file",
    "mmap.mmap": "mmap",
    "threading.Thread": "thread",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "concurrent.futures.ProcessPoolExecutor": "executor",
    "concurrent.futures.thread.ThreadPoolExecutor": "executor",
}

# method call on the tracked name that ends its lifetime
_RELEASE_ATTRS = {"close", "shutdown", "terminate", "kill", "detach",
                  "join", "unlink"}

# calls on the tracked name that are plain uses (neither release nor
# escape); everything else on the receiver position is also a use —
# only argument positions can transfer ownership
_MAX_PATHS = 64


@dataclass
class Resource:
    """One acquire fact; ``released``/``escaped`` are the lattice bits."""
    kind: str
    var: str
    line: int
    released: bool = False
    escaped: bool = False

    def live(self) -> bool:
        return not (self.released or self.escaped)


@dataclass
class ExitState:
    kind: str                     # "return" | "fall" | "raise" | "ctor-raise"
    line: int                     # line of the exiting statement (or def)
    facts: List[Resource]
    trail: List[Tuple[int, str]]  # (line, note) branch decisions taken
    replies: int = 0              # ctx.reply/reply_error calls on this path


class _Path:
    __slots__ = ("bind", "facts", "trail", "replies")

    def __init__(self, bind=None, facts=None, trail=None, replies=0):
        self.bind: Dict[str, Resource] = bind or {}
        self.facts: List[Resource] = facts or []
        self.trail: List[Tuple[int, str]] = trail or []
        self.replies = replies

    def fork(self, note: Optional[Tuple[int, str]] = None) -> "_Path":
        remap = {id(f): Resource(f.kind, f.var, f.line, f.released,
                                 f.escaped) for f in self.facts}
        p = _Path({n: remap[id(f)] for n, f in self.bind.items()},
                  [remap[id(f)] for f in self.facts],
                  list(self.trail), self.replies)
        if note:
            p.trail.append(note)
        return p

    def signature(self) -> Tuple:
        return (tuple(sorted((f.kind, f.line, f.released, f.escaped)
                             for f in self.facts)),
                tuple(sorted((n, f.line) for n, f in self.bind.items())),
                self.replies)


class FunctionDataflow:
    """Path-sensitive walk of one function body.

    ``run()`` returns every reachable :class:`ExitState`.  The walk is
    bounded: loop bodies execute zero or one time, the live path set is
    capped at ``_MAX_PATHS`` (deterministically keeping the first states,
    so dropped paths under-report), and unrecognized constructs degrade
    to "escape everything they mention".
    """

    def __init__(self, fn_node: ast.AST, ctx, *, index=None, fninfo=None,
                 ctor_mode: bool = False, reply_recv: Optional[str] = None):
        self.fn = fn_node
        self.ctx = ctx
        self.index = index
        self.fninfo = fninfo
        self.ctor_mode = ctor_mode
        self.reply_recv = reply_recv
        self.reply_recv_escaped = False
        self.exits: List[ExitState] = []
        self.is_generator = any(
            isinstance(n, (ast.Yield, ast.YieldFrom))
            for n in self._walk_pruned(fn_node))
        self._try_depth = 0
        self._in_cleanup = 0
        self._transfers = self._transfer_lines(ctx)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _walk_pruned(root: ast.AST) -> Iterator[ast.AST]:
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _transfer_lines(ctx) -> Dict[int, Set[str]]:
        cached = getattr(ctx, "_raylint_transfer_lines", None)
        if cached is not None:
            return cached
        out: Dict[int, Set[str]] = {}
        for lineno, text in enumerate(ctx.source.splitlines(), start=1):
            m = _TRANSFER_RE.search(text)
            if m:
                out[lineno] = {t.strip() for t in m.group(1).split(",")}
        ctx._raylint_transfer_lines = out
        return out

    def _transferred(self, line: int, kind: str) -> bool:
        for cand in (line, line - 1):
            tags = self._transfers.get(cand)
            if tags and ({kind, "all"} & tags):
                return True
        return False

    def _acquire_kind(self, call: ast.Call) -> Optional[str]:
        name = _resolved_dotted(call.func, self.ctx)
        if name is None:
            return None
        kind = ACQUIRE_TABLE.get(name)
        if kind == "thread":
            for kw in call.keywords:
                if kw.arg == "daemon" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is True:
                    return None  # daemon threads are fire-and-forget
        if kind is None and isinstance(call.func, ast.Attribute) and \
                call.func.attr == "accept" and not call.args:
            return "socket"      # conn, addr = lsock.accept()
        return kind

    def _callee_keeps_arg(self, call: ast.Call, name: str) -> bool:
        """True unless the resolved project callee only *borrows* the
        parameter the tracked name is bound to (no store/return/forward,
        no release).  Unresolvable callees keep their arguments — the
        under-approximation direction."""
        if self.index is None or self.fninfo is None:
            return True
        site = self.fninfo.site_by_node.get(id(call))
        if site is None or site.target not in self.index.functions:
            return True
        target = self.index.functions[site.target]
        params = _param_names(target.node)
        # map the argument position/keyword onto the callee parameter
        bound: Optional[str] = None
        offset = 1 if target.cls and params and params[0] in (
            "self", "cls") else 0
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Name) and arg.id == name:
                if i + offset < len(params):
                    bound = params[i + offset]
                break
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name) and kw.value.id == name:
                bound = kw.arg
                break
        if bound is None:
            return True           # *args / nested position: assume kept
        verdict = _param_summary(target).get(bound, "owns")
        return verdict != "borrows"

    # -- expression scanning ----------------------------------------------

    def _scan_expr(self, node: Optional[ast.AST], path: _Path,
                   escape: bool = False) -> None:
        """Process one expression: count replies, apply releases, and
        escape any tracked name in an ownership-transferring position.
        ``escape=True`` force-escapes every tracked name mentioned
        (return/raise/yield values)."""
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                # capture by a nested scope: ownership leaves this walk
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Name) and \
                            inner.id in path.bind:
                        path.bind[inner.id].escaped = True
                    if isinstance(inner, ast.Name) and \
                            inner.id == self.reply_recv:
                        self.reply_recv_escaped = True
                continue
            if isinstance(sub, ast.Call):
                self._scan_call(sub, path)
            elif isinstance(sub, (ast.Tuple, ast.List, ast.Set, ast.Dict,
                                  ast.Starred, ast.Await, ast.Yield,
                                  ast.YieldFrom)):
                for name in ast.walk(sub):
                    if isinstance(name, ast.Name) and name.id in path.bind:
                        path.bind[name.id].escaped = True
        if escape:
            for name in ast.walk(node):
                if isinstance(name, ast.Name) and name.id in path.bind:
                    path.bind[name.id].escaped = True

    def _scan_call(self, call: ast.Call, path: _Path) -> None:
        func = call.func
        if isinstance(func, ast.Attribute):
            recv = _dotted(func.value)
            if recv is not None and recv in path.bind and \
                    func.attr in _RELEASE_ATTRS:
                path.bind[recv].released = True
            if self.ctor_mode and recv is not None and \
                    func.attr in _RELEASE_ATTRS:
                fact = path.bind.get(recv)
                if fact is not None:
                    fact.released = True
            if self.reply_recv is not None and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id == self.reply_recv and \
                    func.attr in ("reply", "reply_error"):
                path.replies += 1
        # contextlib.closing(v) and friends adopt the resource
        dotted = _resolved_dotted(func, self.ctx) or ""
        adopting = dotted.endswith(("closing", "ExitStack.enter_context"))
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in path.bind:
                if adopting:
                    path.bind[arg.id].released = True
                elif self._callee_keeps_arg(call, arg.id):
                    path.bind[arg.id].escaped = True
            if self.reply_recv is not None and \
                    isinstance(arg, ast.Name) and \
                    arg.id == self.reply_recv:
                self.reply_recv_escaped = True

    # -- statement walking -------------------------------------------------

    def run(self) -> List[ExitState]:
        body = getattr(self.fn, "body", [])
        outcomes = self._exec_block(body, _Path())
        last = getattr(body[-1], "end_lineno", body[-1].lineno) if body \
            else self.fn.lineno
        for st, ex in outcomes:
            if ex is None:
                if self.ctor_mode:
                    # falling off the end of __init__ is a successful
                    # construction: self.* resources now belong to the
                    # instance the caller receives
                    for name, fact in st.bind.items():
                        if name.startswith("self."):
                            fact.escaped = True
                self._record(st, "fall", last)
            elif ex[0] in ("return", "raise", "ctor-raise"):
                self._record(st, ex[0], ex[1])
            else:                 # stray break/continue: treat as fall
                self._record(st, "fall", ex[1])
        return self.exits

    def _record(self, st: _Path, kind: str, line: int) -> None:
        self.exits.append(ExitState(kind, line, list(st.facts),
                                    list(st.trail), st.replies))

    def _dedup(self, paths: List[_Path]) -> List[_Path]:
        seen: Set[Tuple] = set()
        out: List[_Path] = []
        for p in paths:
            sig = p.signature()
            if sig not in seen:
                seen.add(sig)
                out.append(p)
            if len(out) >= _MAX_PATHS:
                break
        return out

    def _exec_block(self, stmts: Sequence[ast.stmt], state: _Path,
                    ) -> List[Tuple[_Path, Optional[Tuple[str, int]]]]:
        outcomes, _ = self._exec_block_prefixes(stmts, [state])
        return outcomes

    def _exec_block_prefixes(self, stmts: Sequence[ast.stmt],
                             pending: List[_Path]):
        """Run *stmts* over the pending path set.  Returns ``(outcomes,
        prefixes)`` where outcomes are ``(path, exit)`` pairs (exit is
        ``None`` for fall-through) and prefixes snapshots the live path
        set before each statement — the states an exception edge out of a
        ``try`` body can observe.  The state after the *last* statement
        is deliberately not a prefix: a body that ran to completion did
        not raise."""
        outcomes: List[Tuple[_Path, Optional[Tuple[str, int]]]] = []
        prefixes: List[_Path] = []
        for stmt in stmts:
            # "state before stmt" is the state an exception raised *by*
            # stmt exposes — except when stmt is a Try (its own raise
            # outcomes carry the exact post-finally state) or a pure
            # release call (a close() that raises still released the fd)
            if not isinstance(stmt, ast.Try) and \
                    not self._is_release_stmt(stmt):
                prefixes.extend(p.fork() for p in pending)
            nxt: List[_Path] = []
            for st in pending:
                if self.ctor_mode and self._try_depth == 0 and \
                        self._in_cleanup == 0 and \
                        not isinstance(stmt, (ast.Try, ast.Return,
                                              ast.Raise)) and \
                        not self._is_release_stmt(stmt) and \
                        any(isinstance(n, ast.Call)
                            for n in ast.walk(stmt)) and \
                        any(f.live() for f in st.facts):
                    # constructor exception-safety: this call aborting
                    # __init__ strands everything the instance owns
                    outcomes.append((
                        st.fork((stmt.lineno, "raises")),
                        ("ctor-raise", stmt.lineno)))
                for st2, ex in self._exec_stmt(stmt, st):
                    if ex is None:
                        nxt.append(st2)
                    else:
                        outcomes.append((st2, ex))
            pending = self._dedup(nxt)
            if not pending:
                break
        outcomes.extend((st, None) for st in pending)
        return outcomes, self._dedup(prefixes)

    @staticmethod
    def _is_release_stmt(stmt: ast.stmt) -> bool:
        """A bare ``x.close()`` / ``pool.shutdown()`` statement.  Even
        when such a call raises, the underlying handle is released, so
        the state *before* it is not a real exception edge."""
        return (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr in _RELEASE_ATTRS)

    def _known_branch(self, test: ast.expr, st: _Path) -> Optional[bool]:
        """Statically decide ``if`` tests over bound resources.  A name
        bound to a live fact came from a successful acquire, so it is
        neither ``None`` nor falsy on this path.  Returns True (then
        branch only), False (else only), or None (unknown)."""
        def bound(node: ast.AST) -> bool:
            name = node.id if isinstance(node, ast.Name) else _dotted(node)
            return bool(name) and name in st.bind
        if bound(test):
            return True
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and bound(test.operand):
            return False
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                bound(test.left) and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                return False
            if isinstance(test.ops[0], ast.IsNot):
                return True
        return None

    def _bind_acquire(self, target: ast.AST, call: ast.Call, kind: str,
                      st: _Path, fact: Optional[Resource] = None) -> Resource:
        if fact is None:
            fact = Resource(kind, "", call.lineno)
            if self._transferred(call.lineno, kind):
                fact.escaped = True
            st.facts.append(fact)
        if isinstance(target, ast.Name):
            fact.var = target.id
            st.bind[target.id] = fact
        elif isinstance(target, ast.Tuple) and target.elts and \
                isinstance(target.elts[0], ast.Name):
            # conn, addr = lsock.accept() / a, b = socketpair()
            fact.var = target.elts[0].id
            st.bind[target.elts[0].id] = fact
        elif self.ctor_mode and isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            # self.x = acquire(): owned by the half-built instance
            fact.var = _dotted(target) or "self.?"
            st.bind[fact.var] = fact
        else:
            fact.escaped = True   # stored somewhere we do not model
        return fact

    def _exec_stmt(self, stmt: ast.stmt, st: _Path,
                   ) -> List[Tuple[_Path, Optional[Tuple[str, int]]]]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            self._scan_expr(stmt, st)   # capture check only
            return [(st, None)]
        if isinstance(stmt, ast.Return):
            self._scan_expr(stmt.value, st, escape=True)
            if self.ctor_mode:
                # returning from __init__ hands the instance (and its
                # self.* resources) back to the caller
                for name, fact in st.bind.items():
                    if name.startswith("self."):
                        fact.escaped = True
            return [(st, ("return", stmt.lineno))]
        if isinstance(stmt, ast.Raise):
            self._scan_expr(stmt.exc, st, escape=True)
            if stmt.cause is not None:
                self._scan_expr(stmt.cause, st, escape=True)
            return [(st, ("raise", stmt.lineno))]
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return [(st, ("loop", stmt.lineno))]
        if isinstance(stmt, (ast.Pass, ast.Import, ast.ImportFrom,
                             ast.Global, ast.Nonlocal)):
            return [(st, None)]
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    st.bind.pop(t.id, None)
            return [(st, None)]
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else \
                [stmt.target]
            value = stmt.value
            if isinstance(value, ast.Call):
                kind = self._acquire_kind(value)
                if kind is not None:
                    # arguments of the acquire call itself may carry facts
                    self._scan_call(value, st)
                    fact = None
                    for t in targets:
                        fact = self._bind_acquire(t, value, kind, st, fact)
                    return [(st, None)]
            if isinstance(value, ast.Name) and value.id in st.bind:
                fact = st.bind[value.id]
                for t in targets:
                    if isinstance(t, ast.Name):
                        st.bind[t.id] = fact       # alias
                    else:
                        fact.escaped = True        # stored
                return [(st, None)]
            self._scan_expr(value, st)
            for t in targets:
                if not isinstance(t, ast.Name):
                    # a store target mentioning a tracked name escapes it
                    self._scan_expr(t, st, escape=True)
                elif t.id in st.bind:
                    st.bind.pop(t.id)              # rebound: drop binding
            return [(st, None)]
        if isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value, st)
            return [(st, None)]
        if isinstance(stmt, (ast.Expr, ast.Assert)):
            self._scan_expr(stmt.value if isinstance(stmt, ast.Expr)
                            else stmt.test, st)
            return [(st, None)]
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, st)
            branch = self._known_branch(stmt.test, st)
            out = []
            if branch is not False:
                then = st.fork((stmt.lineno, "then"))
                out.extend(self._exec_block(stmt.body, then))
            if branch is not True:
                other = st.fork((stmt.lineno, "else"))
                if stmt.orelse:
                    out.extend(self._exec_block(stmt.orelse, other))
                else:
                    out.append((other, None))
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._scan_expr(stmt.test, st)
            else:
                self._scan_expr(stmt.iter, st)
                for name in ast.walk(stmt.target):
                    if isinstance(name, ast.Name):
                        st.bind.pop(name.id, None)
            out = []
            once = st.fork((stmt.lineno, "loop"))
            for st2, ex in self._exec_block(stmt.body, once):
                if ex is None or ex[0] == "loop":
                    out.append((st2, None))        # rejoin after the loop
                else:
                    out.append((st2, ex))
            skip = st.fork((stmt.lineno, "loop-skip"))
            if stmt.orelse:
                out.extend(self._exec_block(stmt.orelse, skip))
            else:
                out.append((skip, None))
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call):
                    kind = self._acquire_kind(ce)
                    if kind is not None:
                        self._scan_call(ce, st)
                        # `with acquire() as v`: closed on every exit
                        fact = Resource(kind, "", ce.lineno, released=True)
                        st.facts.append(fact)
                        if isinstance(item.optional_vars, ast.Name):
                            fact.var = item.optional_vars.id
                            st.bind[item.optional_vars.id] = fact
                        continue
                    self._scan_expr(ce, st)
                elif isinstance(ce, ast.Name) and ce.id in st.bind:
                    st.bind[ce.id].released = True  # `with v:` closes v
                else:
                    self._scan_expr(ce, st)
            return self._exec_block(stmt.body, st)
        if isinstance(stmt, ast.Try):
            return self._exec_try(stmt, st)
        if isinstance(stmt, ast.Match):
            # match/case: treat every case arm as a branch
            self._scan_expr(stmt.subject, st)
            out = []
            for case in stmt.cases:
                arm = st.fork((case.pattern.lineno, "case"))
                out.extend(self._exec_block(case.body, arm))
            out.append((st.fork((stmt.lineno, "case-none")), None))
            return out
        # anything unmodeled: escape every tracked name it mentions
        self._scan_expr(stmt, st, escape=True)
        return [(st, None)]

    def _exec_try(self, stmt: ast.Try, st: _Path):
        self._try_depth += 1
        body_out, prefixes = self._exec_block_prefixes(stmt.body, [st])
        self._try_depth -= 1
        out: List[Tuple[_Path, Optional[Tuple[str, int]]]] = []
        normal = [o for o, ex in body_out if ex is None]
        raised = [(o, ex) for o, ex in body_out
                  if ex is not None and ex[0] == "raise"]
        other_exits = [(o, ex) for o, ex in body_out
                       if ex is not None and ex[0] != "raise"]
        # exception states: after any prefix of the body, or an explicit
        # raise inside it
        exc_states = self._dedup(prefixes + [o for o, _ in raised])
        if stmt.handlers:
            # handler bodies are already on the failure path: the ctor
            # abort model does not second-guess cleanup code raising
            self._in_cleanup += 1
            for handler in stmt.handlers:
                for es in exc_states:
                    hs = es.fork((handler.lineno, "except"))
                    if handler.name:
                        hs.bind.pop(handler.name, None)
                    out.extend(self._exec_block(handler.body, hs))
            self._in_cleanup -= 1
        else:
            out.extend((o.fork((stmt.lineno, "error")), ("raise", ex[1]))
                       for o, ex in raised)
            if stmt.finalbody:
                # try/finally with no handler: the finally also runs on
                # the unwind of an exception thrown mid-body
                out.extend((es.fork((stmt.lineno, "error")),
                            ("raise", stmt.lineno)) for es in exc_states)
        if stmt.orelse:
            done, _ = self._exec_block_prefixes(stmt.orelse, normal)
            out.extend(done)
        else:
            out.extend((o, None) for o in normal)
        out.extend(other_exits)
        if not stmt.finalbody:
            return out
        final: List[Tuple[_Path, Optional[Tuple[str, int]]]] = []
        self._in_cleanup += 1
        for o, ex in self._dedup_outcomes(out):
            for fo, fex in self._exec_block(stmt.finalbody, o):
                final.append((fo, fex if fex is not None else ex))
        self._in_cleanup -= 1
        return final

    def _dedup_outcomes(self, outcomes):
        seen: Set[Tuple] = set()
        out = []
        for o, ex in outcomes:
            sig = (o.signature(), ex)
            if sig not in seen:
                seen.add(sig)
                out.append((o, ex))
            if len(out) >= _MAX_PATHS:
                break
        return out


def _param_names(fn_node: ast.AST) -> List[str]:
    a = fn_node.args
    return [x.arg for x in
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]


_summary_cache: Dict[int, Dict[str, str]] = {}


def _param_summary(fninfo) -> Dict[str, str]:
    """Per-parameter ownership verdict for a resolved callee:
    ``"borrows"`` (the function only reads/uses it), ``"releases"``
    (calls a release method on it), or ``"owns"`` (stores, returns,
    forwards, or captures it — ownership transfers in).  One level deep
    and deliberately conservative: anything unclear is ``"owns"``."""
    cached = _summary_cache.get(id(fninfo))
    if cached is not None:
        return cached
    verdict: Dict[str, str] = {p: "borrows" for p in _param_names(fninfo.node)}

    def mark(name: str, v: str) -> None:
        if name in verdict and verdict[name] != "owns":
            verdict[name] = v

    for node in FunctionDataflow._walk_pruned(fninfo.node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name):
                    mark(inner.id, "owns")
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.attr in _RELEASE_ATTRS:
                mark(node.func.value.id, "releases")
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    mark(arg.id, "owns")
        elif isinstance(node, (ast.Return, ast.Raise)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name):
                    mark(inner.id, "owns")
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(node, "value", None)
            targets = node.targets if isinstance(node, ast.Assign) else \
                [node.target]
            if isinstance(value, ast.Name):
                if not all(isinstance(t, ast.Name) for t in targets):
                    mark(value.id, "owns")
                else:
                    for t in targets:
                        mark(value.id, "owns")  # aliased: lose track
            for t in targets:
                if not isinstance(t, ast.Name):
                    for inner in ast.walk(t):
                        if isinstance(inner, ast.Name):
                            mark(inner.id, "owns")
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set, ast.Dict,
                               ast.Yield, ast.YieldFrom)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name):
                    mark(inner.id, "owns")
    _summary_cache[id(fninfo)] = verdict
    return verdict


def resource_leaks(fninfo, index) -> List[Tuple[Resource, ExitState]]:
    """Leak candidates for one function: for each acquire fact, the first
    exit state that reaches a function exit with the fact still live.
    Generators and async functions are skipped (their frames suspend with
    resources legitimately live)."""
    node = fninfo.node
    if isinstance(node, ast.AsyncFunctionDef):
        return []
    flow = FunctionDataflow(node, fninfo.ctx, index=index, fninfo=fninfo,
                            ctor_mode=(fninfo.name == "__init__"))
    if flow.is_generator:
        return []
    leaks: List[Tuple[Resource, ExitState]] = []
    seen: Set[Tuple[str, int]] = set()
    for exit_state in flow.run():
        for fact in exit_state.facts:
            if fact.live() and (fact.kind, fact.line) not in seen:
                seen.add((fact.kind, fact.line))
                leaks.append((fact, exit_state))
    return leaks


# --------------------------------------------------------------------------
# deadline-propagation facts (R17)

_DEADLINEISH = re.compile(r"deadline|budget|timeout", re.IGNORECASE)
_QUEUEISH = re.compile(r"(^|[._])(q|queue|inbox)", re.IGNORECASE)
_LOCKISH = re.compile(r"(^|[._])(lock|mutex|cv|cond|sem)", re.IGNORECASE)


def deadline_params(fn_node: ast.AST) -> List[str]:
    """Parameters that carry a time budget the function must honor."""
    return [p for p in _param_names(fn_node)
            if _DEADLINEISH.search(p) and p not in ("self", "cls")]


def arms_backoff_budget(fn_node: ast.AST) -> Optional[int]:
    """Line of a ``BackoffPolicy(deadline_s=...)`` construction with a
    non-zero budget, else None — arming a retry deadline makes the
    function a deadline scope even without a deadline parameter."""
    for node in FunctionDataflow._walk_pruned(fn_node):
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            if name.split(".")[-1] == "BackoffPolicy":
                for kw in node.keywords:
                    if kw.arg == "deadline_s" and not (
                            isinstance(kw.value, ast.Constant) and
                            kw.value.value in (0, None)):
                        return node.lineno
    return None


def _has_kwarg(call: ast.Call, *names: str) -> bool:
    return any(kw.arg in names for kw in call.keywords)


def naked_blocking(fn_node: ast.AST, ctx) -> List[Tuple[int, str]]:
    """(line, description) of unbounded blocking primitives written
    directly in this function: ``.wait()`` / zero-arg ``.join()`` /
    ``.result()`` without a timeout, zero-arg lock ``.acquire()``,
    zero-arg queue ``.get()``, and ``concurrent.futures.wait`` without a
    ``timeout=``.  ``time.sleep`` is bounded by construction and stays
    out of this set (R7/R10 cover its pathologies)."""
    out: List[Tuple[int, str]] = []
    for node in FunctionDataflow._walk_pruned(fn_node):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        attr = node.func.attr
        recv = _dotted(node.func.value) or ""
        resolved = _resolved_dotted(node.func, ctx) or ""
        if attr == "wait":
            if resolved in ("concurrent.futures.wait", "futures.wait"):
                if not _has_kwarg(node, "timeout"):
                    out.append((node.lineno,
                                "concurrent.futures.wait() without timeout"))
            elif not node.args and not _has_kwarg(node, "timeout"):
                out.append((node.lineno, f"{recv}.wait() without timeout"))
        elif attr == "join" and not node.args and not node.keywords:
            out.append((node.lineno, f"{recv}.join() without timeout"))
        elif attr == "result" and not node.args and \
                not _has_kwarg(node, "timeout"):
            out.append((node.lineno, f"{recv}.result() without timeout"))
        elif attr == "acquire" and not node.args and not node.keywords \
                and _LOCKISH.search(recv):
            out.append((node.lineno, f"{recv}.acquire() without timeout"))
        elif attr == "get" and not node.args and not node.keywords and \
                _QUEUEISH.search(recv):
            out.append((node.lineno, f"{recv}.get() without timeout"))
    return out


# --------------------------------------------------------------------------
# protocol-conformance facts (R18)

# attribute names that hand a pb.<METHOD> to the wire
# helper primitives that forward a protocol constant onto the wire keep
# to a naming convention ("..._call", "send_...", "..._push"): a repo-local
# contract the scanner leans on instead of resolving dynamic dispatch
_SENDISH_RE = re.compile(r"(^|_)(call|send|push|enqueue)(_|$|\b)")

SEND_ATTRS = {"call", "call_async", "call_burst", "send_oneway", "_call",
              "push", "child", "enqueue"}


def _pb_method(node: ast.AST, ctx) -> Optional[str]:
    """``pb.PUSH_TASK``-style protocol constant, resolved through import
    aliases; None for anything else."""
    if not isinstance(node, ast.Attribute) or not node.attr.isupper():
        return None
    prefix = _dotted(node.value)
    if prefix is None:
        return None
    head = prefix.split(".")[0]
    origin = ctx.import_origin.get(head, prefix)
    if prefix == "pb" or prefix.endswith(".pb") or \
            origin.endswith((".pb", "_pb2")) or \
            "protocol" in origin:
        return node.attr
    return None


def protocol_sends(ctxs) -> List[Tuple[str, object, int]]:
    """(method, ctx, line) for every protocol constant handed to a send
    primitive (``client.call(pb.M, ...)``, ``ctx.push(pb.M, ...)``,
    batcher ``enqueue``, ...) or baked into an ``Envelope(method=pb.M)``
    construction."""
    out: List[Tuple[str, object, int]] = []
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            leaf = dotted.split(".")[-1]
            is_send = (isinstance(node.func, ast.Attribute)
                       and node.func.attr in SEND_ATTRS) or \
                bool(_SENDISH_RE.search(leaf))
            is_envelope = leaf == "Envelope"
            # a pb constant bound to a kwarg literally named ``method`` is
            # a send regardless of the helper's name: the helper forwards
            # it into an Envelope (``_push_task_remote(..., method=pb.X)``)
            has_method_kw = any(kw.arg == "method" for kw in node.keywords)
            if not (is_send or is_envelope or has_method_kw):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    m = _pb_method(sub, ctx)
                    if m is not None:
                        out.append((m, ctx, sub.lineno))
    return out


def protocol_handlers(ctxs) -> List[Tuple[str, object, int]]:
    """(method, ctx, line) for every dispatch-side comparison of a
    ``.method`` field against a protocol constant (``if method ==
    pb.PING``, ``env.method != pb.AUTH``, ``method in (pb.A, pb.B)``)."""
    out: List[Tuple[str, object, int]] = []
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            names = [(_dotted(s) or "") for s in sides]
            if not any("method" in n.lower() for n in names):
                continue
            for side in sides:
                for sub in ast.walk(side):
                    m = _pb_method(sub, ctx)
                    if m is not None:
                        out.append((m, ctx, sub.lineno))
    return out


_NATIVE_CASE_RE = re.compile(r"case\s+raytpu::([A-Z][A-Z0-9_]*)\s*:")
_NATIVE_CMP_RE = re.compile(r"method\(\)\s*[!=]=\s*raytpu::([A-Z][A-Z0-9_]*)")
_NATIVE_SEND_RE = re.compile(r"set_method\(\s*raytpu::([A-Z][A-Z0-9_]*)")


def native_protocol_facts(native_dir: str) -> Tuple[Set[str], Set[str]]:
    """(handled, sent) method names extracted from the C++ state service
    (``case raytpu::M:`` dispatch arms, ``env.method() == raytpu::M``
    guards, ``set_method(raytpu::M)`` pushes).  Missing sources degrade
    to empty sets — the python-side cross-check then stands alone."""
    handled: Set[str] = set()
    sent: Set[str] = set()
    if not os.path.isdir(native_dir):
        return handled, sent
    for fname in sorted(os.listdir(native_dir)):
        if not fname.endswith((".cc", ".h")):
            continue
        try:
            with open(os.path.join(native_dir, fname),
                      encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        handled.update(_NATIVE_CASE_RE.findall(text))
        handled.update(_NATIVE_CMP_RE.findall(text))
        sent.update(_NATIVE_SEND_RE.findall(text))
    return handled, sent


_PROTO_ENUM_RE = re.compile(
    r"enum\s+Method\s*\{(.*?)\}", re.DOTALL)
_PROTO_VALUE_RE = re.compile(r"([A-Z][A-Z0-9_]*)\s*=\s*(\d+)\s*;")


def proto_method_names(proto_path: str) -> Set[str]:
    """Names of the ``Method`` enum in raytpu.proto (empty when the proto
    is not under the lint roots, e.g. in the fixture corpus)."""
    try:
        with open(proto_path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return set()
    m = _PROTO_ENUM_RE.search(text)
    if not m:
        return set()
    return {name for name, _num in _PROTO_VALUE_RE.findall(m.group(1))}


def reply_candidates(fninfo) -> Optional[str]:
    """The RpcContext-style parameter of a handler function, when the
    function replies through it directly (``ctx.reply(...)`` /
    ``ctx.reply_error(...)``); None when the function is not a reply
    site."""
    params = _param_names(fninfo.node)
    candidates = [p for p in params if p == "ctx" or p.endswith("_ctx")]
    for node in FunctionDataflow._walk_pruned(fninfo.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("reply", "reply_error") and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in candidates:
            return node.func.value.id
    return None


# --------------------------------------------------------------------------
# node-lifecycle state machine (R18, PR 8 correlation)

# The declared machine: NodeInfo.state "" (legacy ALIVE) -> DRAINING ->
# DRAINED, any live state may die.  This table is the static contract the
# extracted transitions are checked against; ARCHITECTURE.md documents it
# next to the PR 8 drain orchestrator.
NODE_LIFECYCLE = {
    "states": ("", "ALIVE", "DRAINING", "DRAINED", "DEAD"),
    "transitions": frozenset({
        ("", "DRAINING"), ("ALIVE", "DRAINING"),
        ("DRAINING", "DRAINED"),
        ("", "DEAD"), ("ALIVE", "DEAD"),
        ("DRAINING", "DEAD"), ("DRAINED", "DEAD"),
    }),
}

_LIFECYCLE_VOCAB = {"ALIVE", "DRAINING", "DRAINED", "DEAD"}


def lifecycle_writes(ctxs) -> List[Tuple[object, int, str, Set[str], str,
                                         Optional[int]]]:
    """Statically extracted node-lifecycle transitions: every
    ``<recv>.state = "<STATE>"`` write whose value is in the lifecycle
    vocabulary, as ``(ctx, line, recv, from_states, to_state,
    guard_line)``.  ``from_states`` is the set the innermost dominating
    ``<recv>.state == "X"`` guard admits, or ``{"*"}`` when the write is
    unguarded (legal iff the target state is reachable at all)."""
    out = []

    def visit(node, ctx, guards):
        if isinstance(node, ast.If):
            cond_guards = list(guards)
            g = _state_guard(node.test)
            if g is not None:
                cond_guards = cond_guards + [(g[0], g[1], node.lineno)]
            for child in node.body:
                visit(child, ctx, cond_guards)
            for child in node.orelse:
                visit(child, ctx, guards)   # else: the guard is unknown
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "state" and \
                        isinstance(node.value, ast.Constant) and \
                        node.value.value in _LIFECYCLE_VOCAB:
                    recv = _dotted(t.value) or "?"
                    froms, guard_line = {"*"}, None
                    for grecv, gstates, gline in reversed(guards):
                        if grecv == recv:
                            froms, guard_line = gstates, gline
                            break
                    out.append((ctx, t.lineno, recv, froms,
                                node.value.value, guard_line))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, ctx, [])   # a def's body runs elsewhere:
            elif not isinstance(child, ast.Lambda):  # guards don't dominate
                visit(child, ctx, guards)

    def _state_guard(test):
        """(recv, {states}) for `<recv>.state == "X"` / `in (..)`."""
        if not isinstance(test, ast.Compare) or len(test.ops) != 1 or \
                not isinstance(test.ops[0], (ast.Eq, ast.In)):
            return None
        left, right = test.left, test.comparators[0]
        if not (isinstance(left, ast.Attribute) and left.attr == "state"):
            return None
        recv = _dotted(left.value)
        if recv is None:
            return None
        if isinstance(test.ops[0], ast.Eq) and \
                isinstance(right, ast.Constant) and \
                isinstance(right.value, str):
            return recv, {right.value}
        if isinstance(test.ops[0], ast.In) and \
                isinstance(right, (ast.Tuple, ast.List, ast.Set)):
            vals = {e.value for e in right.elts
                    if isinstance(e, ast.Constant) and
                    isinstance(e.value, str)}
            if vals:
                return recv, vals
        return None

    for ctx in ctxs:
        for child in ast.iter_child_nodes(ctx.tree):
            visit(child, ctx, [])
    return out


# --------------------------------------------------------------------------
# field-level thread-safety facts (R23-R25)
#
# Per-function shared-attribute access records, in-function atomicity-split
# candidates, `# raylint: guarded-by(...)` declarations, and the per-module
# tracked-global/atomic-attribute sets.  Every output here is JSON-able and
# a pure function of ONE file's source, so the linter caches it under the
# file's content hash exactly like the stitch facts; the callgraph layer
# (ProjectIndex.field_plan) joins the records with thread contexts and
# interprocedural must-hold locksets.
#
# Under-approximation stance, same polarity as the rest of this module: a
# construct the scanner does not understand contributes no access record,
# so the field rules can miss a race through dynamic attribute names or
# getattr() but never report a site that does not textually exist.

_GUARDED_RE = re.compile(r"#\s*raylint:\s*guarded-by\(([^)]+)\)")

#: method names that mutate their receiver in place — a call through a
#: shared attribute with one of these is a write for race purposes
_MUTATOR_ATTRS = frozenset({
    "append", "extend", "insert", "pop", "popitem", "remove", "discard",
    "add", "clear", "update", "setdefault", "sort", "reverse",
    "appendleft", "popleft", "rotate", "put", "put_nowait",
})

#: constructor leaf names whose instances are internally synchronized (or
#: atomic by construction, like itertools.count under the GIL); attributes
#: assigned from them are exempt from the field rules — calling their
#: methods IS the synchronization
ATOMIC_TYPE_LEAVES = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "deque",
    "Event", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
    "local", "Lock", "RLock", "count", "Thread", "Timer",
    "ThreadPoolExecutor", "ProcessPoolExecutor",
})

#: attributes/globals the field analysis never tracks: dunders and the
#: locks themselves (lock objects are the synchronization, not the state)
_FIELD_SKIP_RE = re.compile(
    r"(^__)|((^|[._])(lock|mutex|cv|cond|sem))", re.IGNORECASE)


def module_global_names(tree: ast.AST) -> Set[str]:
    """Module globals the field analysis tracks: names assigned at module
    top level plus every name in an ``ast.Global`` statement (container
    globals are mutated without ``global``, so top-level binding is the
    signal that matters)."""
    out: Set[str] = set()
    for stmt in tree.body:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


class _FieldScan:
    """One function's shared-attribute accesses and atomicity splits.

    Mirrors the ``held``-stack walk of ``ProjectIndex._analyze`` but also
    tracks *acquisition identity* (the ``with``-statement line), so a
    release-and-retake of the same lock between a read and its dependent
    write is visible — that gap is exactly what R24 reports.  Emits:

    - access records ``[line, key, mode, locks, wconst]`` where ``key`` is
      ``mod:Cls.attr`` for ``self.attr`` or ``mod.name`` for a module
      global, ``mode`` is ``read``/``write``/``mutate``, ``locks`` is the
      lexically-held lock-id set, and ``wconst`` is ``"flag"`` for
      True/False/None constant writes (the bool fast-path suppression);
    - split records ``[key, read_line, write_line, kind]`` for
      check-then-act and read-modify-write sequences whose read and write
      share no lock acquisition (double-checked re-reads under the write's
      acquisition suppress the candidate).
    """

    def __init__(self, fn, index, global_names: Set[str]):
        self.fn = fn
        self.index = index
        self.mod = fn.module
        self.global_names = global_names
        self.accesses: List[list] = []
        self.splits: List[list] = []
        self._held: List[Tuple[str, int]] = []     # (lock id, with line)
        self._reads: List[Tuple[str, int, frozenset]] = []  # scan order
        self._checks: List[Dict[str, Tuple[int, frozenset]]] = []
        self._bind: Dict[str, Tuple[str, int, frozenset]] = {}
        self._gdecls: Set[str] = set()
        self._locals: Set[str] = set()
        a = fn.node.args
        for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
            self._locals.add(p.arg)
        for va in (a.vararg, a.kwarg):
            if va is not None:
                self._locals.add(va.arg)
        for node in FunctionDataflow._walk_pruned(fn.node):
            if isinstance(node, ast.Global):
                self._gdecls.update(node.names)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                self._locals.add(node.id)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                self._locals.add(node.name)
        self._locals -= self._gdecls

    def run(self) -> Tuple[List[list], List[list]]:
        for stmt in self.fn.node.body:
            self._scan(stmt)
        return self.accesses, self.splits

    # -- keys --------------------------------------------------------------

    def _self_key(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and self.fn.cls and \
                not _FIELD_SKIP_RE.search(node.attr):
            return f"{self.mod}:{self.fn.cls}.{node.attr}"
        return None

    def _global_key(self, node: ast.Name) -> Optional[str]:
        nid = node.id
        if _FIELD_SKIP_RE.search(nid):
            return None
        if nid in self._gdecls or (nid in self.global_names
                                   and nid not in self._locals):
            return f"{self.mod}.{nid}"
        return None

    def _extern_key(self, node: ast.AST) -> Optional[str]:
        """``othermod.NAME`` write target, resolved through this module's
        import aliases (validated against the target module's tracked
        globals at plan time — only writes are recorded cross-module, so
        stdlib attribute noise never enters the fact store)."""
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)):
            return None
        if _FIELD_SKIP_RE.search(node.attr):
            return None
        mod = self.index.modules.get(self.mod)
        target = mod.imports.get(node.value.id) if mod is not None else None
        if target is None:
            return None
        return f"{target}.{node.attr}"

    # -- recording ---------------------------------------------------------

    def _rec(self, line: int, key: str, mode: str, wconst: str = "") -> None:
        locks = sorted({l for l, _ in self._held})
        self.accesses.append([line, key, mode, locks, wconst])
        if mode == "read":
            acqs = frozenset(a for _, a in self._held)
            self._reads.append((key, line, acqs))

    def _note_check_then_act(self, key: str, wline: int,
                             wacqs: frozenset) -> None:
        # nearest enclosing if/while test that read this key
        for frame in reversed(self._checks):
            info = frame.get(key)
            if info is None:
                continue
            tline, tacqs = info
            if (tacqs & wacqs) or not (tacqs | wacqs):
                return              # same acquisition spans both, or R23's job
            rechecked = any(
                k == key and line > tline and (acqs & wacqs)
                for k, line, acqs in self._reads)
            if not rechecked:       # double-checked locking stays quiet
                self.splits.append([key, tline, wline, "check-then-act"])
            return

    def _note_rmw(self, key: str, wline: int, wacqs: frozenset,
                  value_reads, value_names) -> None:
        cands = [(line, acqs) for k, line, acqs in value_reads if k == key]
        for name in value_names:
            b = self._bind.get(name)
            if b is not None and b[0] == key:
                cands.append((b[1], b[2]))
        for rline, racqs in cands:
            if (racqs & wacqs) or not (racqs | wacqs):
                continue
            self.splits.append([key, rline, wline, "read-modify-write"])
            return

    def _write_target(self, t: ast.AST, wconst: str,
                      value_reads, value_names) -> None:
        wacqs = frozenset(a for _, a in self._held)
        if isinstance(t, ast.Attribute):
            key = self._self_key(t) or self._extern_key(t)
            if key:
                self._rec(t.lineno, key, "write", wconst)
                self._note_check_then_act(key, t.lineno, wacqs)
                self._note_rmw(key, t.lineno, wacqs, value_reads,
                               value_names)
                return
            # chained target like ``self.cfg.max = v``: mutates the object
            # held in the inner shared attribute
            inner = None
            if isinstance(t.value, ast.Attribute):
                inner = self._self_key(t.value)
            elif isinstance(t.value, ast.Name):
                inner = self._global_key(t.value)
            if inner:
                self._rec(t.value.lineno, inner, "mutate")
            else:
                self._scan(t.value)
            return
        if isinstance(t, ast.Subscript):
            base = t.value
            key = None
            if isinstance(base, ast.Attribute):
                key = self._self_key(base) or self._extern_key(base)
            elif isinstance(base, ast.Name):
                key = self._global_key(base)
            if key:
                self._rec(base.lineno, key, "mutate")
                self._note_check_then_act(key, base.lineno, wacqs)
                self._note_rmw(key, base.lineno, wacqs, value_reads,
                               value_names)
                self._scan(t.slice)
            else:
                self._scan(base)
                self._scan(t.slice)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._write_target(e, "", value_reads, value_names)
            return
        if isinstance(t, ast.Starred):
            self._write_target(t.value, "", value_reads, value_names)
            return
        if isinstance(t, ast.Name):
            if t.id in self._gdecls and not _FIELD_SKIP_RE.search(t.id):
                key = f"{self.mod}.{t.id}"
                self._rec(t.lineno, key, "write", wconst)
                self._note_check_then_act(key, t.lineno, wacqs)
                self._note_rmw(key, t.lineno, wacqs, value_reads,
                               value_names)
            return

    # -- walk --------------------------------------------------------------

    def _scan(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return                  # nested defs are their own FunctionInfo
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    self._scan(item.context_expr)
                lid = self.index._lock_identity(item.context_expr, self.fn)
                if lid:
                    self._held.append((lid, node.lineno))
                    pushed += 1
            for stmt in node.body:
                self._scan(stmt)
            del self._held[len(self._held) - pushed:]
            return
        if isinstance(node, (ast.If, ast.While)):
            n0 = len(self._reads)
            self._scan(node.test)
            frame: Dict[str, Tuple[int, frozenset]] = {}
            for k, line, acqs in self._reads[n0:]:
                frame.setdefault(k, (line, acqs))
            self._checks.append(frame)
            for stmt in node.body:
                self._scan(stmt)
            for stmt in node.orelse:
                self._scan(stmt)
            self._checks.pop()
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            n0 = len(self._reads)
            if node.value is not None:
                self._scan(node.value)
            value_reads = self._reads[n0:]
            value_names = {n.id for n in ast.walk(node.value)
                           if isinstance(n, ast.Name)
                           and isinstance(n.ctx, ast.Load)} \
                if node.value is not None else set()
            wconst = ""
            if isinstance(node.value, ast.Constant) and \
                    any(node.value.value is v for v in (True, False, None)):
                wconst = "flag"
            targets = list(node.targets) if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                self._write_target(t, wconst, value_reads, value_names)
            if isinstance(node, ast.Assign) and len(targets) == 1 and \
                    isinstance(targets[0], ast.Name) and \
                    targets[0].id not in self._gdecls:
                if value_reads:
                    self._bind[targets[0].id] = value_reads[0]
                else:
                    self._bind.pop(targets[0].id, None)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._write_target(t, "", [], set())
            return
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                recv = f.value
                key = None
                if isinstance(recv, ast.Attribute):
                    key = self._self_key(recv)
                elif isinstance(recv, ast.Name):
                    key = self._global_key(recv)
                if key:
                    mode = "mutate" if f.attr in _MUTATOR_ATTRS else "read"
                    self._rec(recv.lineno, key, mode)
                else:
                    self._scan(recv)
            else:
                self._scan(f)
            for arg in node.args:
                self._scan(arg)
            for kw in node.keywords:
                self._scan(kw.value)
            return
        if isinstance(node, ast.Subscript):
            base = node.value
            key = None
            if isinstance(base, ast.Attribute):
                key = self._self_key(base)
            elif isinstance(base, ast.Name):
                key = self._global_key(base)
            if key:
                self._rec(base.lineno, key, "read")
            else:
                self._scan(base)
            self._scan(node.slice)
            return
        if isinstance(node, ast.Attribute):
            key = self._self_key(node)
            if key:
                mode = "read" if isinstance(node.ctx, ast.Load) else "write"
                self._rec(node.lineno, key, mode)
                return
            self._scan(node.value)
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                key = self._global_key(node)
                if key:
                    self._rec(node.lineno, key, "read")
            return
        for child in ast.iter_child_nodes(node):
            self._scan(child)


def guarded_decls(ctx, module_name: str, index) -> List[list]:
    """``[key, lock_id, line]`` per ``# raylint: guarded-by(...)``
    declaration in *ctx*.  A declaration attaches to the assignment on the
    same line (or the line directly above, like ``allow``); the lock
    expression resolves exactly like ``ProjectIndex._lock_identity``:
    ``self._lock`` -> ``Cls._lock``, a bare name -> ``mod.name``, an
    import-alias attribute -> the defining module's node."""
    comments: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(ctx.source).readline):
            if tok.type == tokenize.COMMENT:
                m = _GUARDED_RE.search(tok.string)
                if m:
                    comments[tok.start[0]] = m.group(1).strip()
    except tokenize.TokenError:
        pass
    if not comments:
        return []
    mod = index.modules.get(module_name)

    def resolve_lock(text: str, clsname: Optional[str]) -> str:
        if text.startswith("self."):
            return f"{clsname or '?'}.{text[5:]}"
        if "." not in text:
            return f"{module_name}.{text}"
        parts = text.split(".")
        if mod is not None and parts[0] in mod.imports and \
                mod.imports[parts[0]] in index.modules:
            return ".".join([mod.imports[parts[0]]] + parts[1:])
        return text

    decls: List[list] = []
    # comment lines claimed by an inline declaration: the line-above
    # fallback must not re-attach them to the *next* statement
    inline_lines: Set[int] = set()

    def attach(stmt, clsname: Optional[str]) -> None:
        lock_txt = None
        for ln in range(stmt.lineno,
                        getattr(stmt, "end_lineno", stmt.lineno) + 1):
            if ln in comments:
                lock_txt = comments[ln]
                inline_lines.add(ln)
                break
        if lock_txt is None and stmt.lineno - 1 not in inline_lines:
            lock_txt = comments.get(stmt.lineno - 1)
        if lock_txt is None:
            return
        lock = resolve_lock(lock_txt, clsname)
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self" and clsname:
                decls.append([f"{module_name}:{clsname}.{t.attr}", lock,
                              stmt.lineno])
            elif isinstance(t, ast.Name):
                key = f"{module_name}:{clsname}.{t.id}" if clsname \
                    else f"{module_name}.{t.id}"
                decls.append([key, lock, stmt.lineno])

    def walk(node, clsname):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
                continue
            if isinstance(child, (ast.Assign, ast.AnnAssign)):
                attach(child, clsname)
            walk(child, clsname)

    walk(ctx.tree, None)
    return decls


def atomic_attr_keys(ctx, module_name: str, index) -> List[str]:
    """Keys of attributes/globals assigned from an internally-synchronized
    constructor (``queue.Queue``, ``threading.Event``,
    ``itertools.count``, ...) — exempt from the field rules."""
    out: Set[str] = set()

    def walk(node, clsname):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
                continue
            if isinstance(child, ast.Assign) and \
                    isinstance(child.value, ast.Call):
                dn = _resolved_dotted(child.value.func, ctx) or ""
                if dn.rsplit(".", 1)[-1] in ATOMIC_TYPE_LEAVES:
                    for t in child.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self" and clsname:
                            out.add(f"{module_name}:{clsname}.{t.attr}")
                        elif isinstance(t, ast.Name) and clsname is None:
                            out.add(f"{module_name}.{t.id}")
            walk(child, clsname)

    walk(ctx.tree, None)
    return sorted(out)
