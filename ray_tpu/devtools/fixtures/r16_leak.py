"""R16 fixture: OS-backed resources must be released on every path.

``leaky_early_return`` strands its socket on the ``not peer`` path;
the other functions show the clean shapes (release on every path,
ownership transfer via return, explicit transfer annotation).
"""
import socket


def leaky_early_return(peer, payload):
    sock = socket.create_connection(peer)
    if not payload:
        return None
    sock.sendall(payload)
    sock.close()
    return True


def clean_all_paths(peer, payload):
    sock = socket.create_connection(peer)
    try:
        sock.sendall(payload)
    finally:
        sock.close()


def clean_ownership_transfer(peer):
    sock = socket.create_connection(peer)
    return sock


def clean_annotated_handoff(peer, registry):
    sock = socket.create_connection(peer)  # raylint: transfer(socket) registry owns it
    registry["peer"] = sock
