"""R29 fixture: the static collective-cost manifest.

Positive case: ``_leak`` psums over a mesh axis no AXIS_ORDER or
Mesh(...) in the tree declares, so the op can never be planned in
comms_manifest.json and would always report as unplanned runtime drift.
Clean twins: ``_ring`` reduces over a declared axis and ``sync`` runs
explicit collective-API ops with a literal group, both of which land in
the manifest.
"""

import jax
from jax.sharding import PartitionSpec as P

from ray_tpu import collective
from ray_tpu._private.jax_compat import shard_map


def _ring(x):
    return jax.lax.psum(x, "tensor")


def _leak(x):
    return jax.lax.psum(x, "ghost_axis")


def build(mesh):
    ok = shard_map(_ring, mesh=mesh, in_specs=(P("tensor"),),
                   out_specs=P("tensor"), check_vma=False)
    leak = shard_map(_leak, mesh=mesh, in_specs=(P("tensor"),),
                     out_specs=P("tensor"), check_vma=False)
    return ok, leak


def sync(t):
    out = collective.allreduce(t, group_name="fixture")
    collective.barrier(group_name="fixture")
    return out
