"""R17 fixture: no naked blocking under a deadline scope.

``drain_with_deadline`` promises to honor its ``deadline`` but reaches
``_flush_unbounded``'s bare ``Event.wait()`` — the witness path the
rule must report.  ``drain_bounded`` passes the budget down.
"""
import threading

DONE = threading.Event()


def drain_with_deadline(deadline):
    _flush_unbounded()
    return deadline


def _flush_unbounded():
    DONE.wait()


def drain_bounded(deadline):
    DONE.wait(deadline)
