"""R26 fixture: direct ``_config.set`` writes to autopilot-owned knobs.

Positives: a literal write to a knob listed in
``ray_tpu/autopilot/knobs.py`` through the bare ``_config`` receiver and
through a module-alias receiver.  Negatives: a write to a knob the
autopilot does not own, a dynamic knob name, a *read* of an owned knob,
and a ``.set`` on an unrelated object.
"""
from ray_tpu._private.config import _config
from ray_tpu._private import config as cfgmod


def bad_direct_set():
    # raylint: allow(config-drift) owned knob lives in the runtime config
    _config.set("data_streams_per_peer", 8)


def bad_alias_set():
    cfgmod._config.set("collective_compression", "q8")


def good_unowned_set():
    _config.set("fixture_live_knob", 3)


def good_dynamic_name(knob):
    cfgmod._config.set(knob, 8)


def good_owned_read():
    # raylint: allow(config-drift) owned knob lives in the runtime config
    return _config.get("data_prefetch_batches")


class _Store(dict):
    def set(self, key, value):
        self[key] = value


def good_unrelated_receiver():
    store = _Store()
    store.set("data_streams_per_peer", 8)
    return store
