"""R19 fixture: distributed deadlock over the stitched call graph.

Positive cases: ``dispatch``'s FWD arm synchronously calls BACK whose
arm synchronously calls FWD back (a cross-daemon wait cycle), and
``send_while_locked`` holds ``_LOCK`` across a synchronous LOCKED send
whose handler re-acquires the same lock.  Clean twins: the SAFE arm
sends fire-and-forget (``call_async`` never waits), and
``send_after_unlock`` drops the lock before touching the wire.
"""

import threading

_LOCK = threading.Lock()


class pb:
    FWD = 1
    BACK = 2
    SAFE = 3
    LOCKED = 4


def dispatch(env, ctx, client):
    if env.method == pb.FWD:
        client.call(pb.BACK, b"")
        ctx.reply(b"")
    elif env.method == pb.BACK:
        client.call(pb.FWD, b"")
        ctx.reply(b"")
    elif env.method == pb.SAFE:
        client.call_async(pb.FWD, b"", None)
        ctx.reply(b"")
    else:
        ctx.reply_error("unknown method")


def send_safe(client):
    client.call_async(pb.SAFE, b"", None)


def locked_dispatch(env, ctx):
    if env.method == pb.LOCKED:
        with _LOCK:
            pass
        ctx.reply(b"")
    else:
        ctx.reply_error("unknown method")


def send_while_locked(client):
    with _LOCK:
        client.call(pb.LOCKED, b"")


def send_after_unlock(client):
    with _LOCK:
        body = b""
    client.call(pb.LOCKED, body)
