"""R28 fixture: implicit reshard across a jitted boundary.

Positive cases: ``bad`` places an array replicated and then feeds a
shard_map whose in_specs pin P('data') — XLA inserts a silent resharding
collective on every call; ``bad_donate`` donates argument 0 but its
out_shardings differ from the donated in_sharding, wasting the
donation.  The clean twins place with the consumer's spec / keep the
donated layout.
"""

import functools

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

_MESH = None


def _one(x):
    return x


_STEP = shard_map(_one, mesh=_MESH, in_specs=(P("data"),),
                  out_specs=P("data"), check_vma=False)


def good(x, mesh):
    x = jax.device_put(x, NamedSharding(mesh, P("data")))
    return _STEP(x)


def bad(x, mesh):
    x = jax.device_put(x, NamedSharding(mesh, P(None)))
    return _STEP(x)


@functools.partial(jax.jit, donate_argnums=(0,),
                   in_shardings=(P("data"), P(None)),
                   out_shardings=P("data"))
def good_donate(state, x):
    return state


@functools.partial(jax.jit, donate_argnums=(0,),
                   in_shardings=(P("data"), P(None)),
                   out_shardings=P(None))
def bad_donate(state, x):
    return state
