"""R27 fixture: mesh/spec consistency over the abstract sharding model.

Positive cases: ``BAD_RULES`` maps a logical axis to a mesh axis no mesh
declares, ``BAD_AXIS_SPEC`` names an unknown mesh axis, ``DUP_SPEC``
binds one mesh axis to two dims of a single PartitionSpec, ``build``
passes a 2-spec ``in_specs`` to a 3-argument mapped function, and
``make_specs``/``override`` use a logical name absent from every rules
table / an unknown override mesh axis.  Clean twins mirror each case
with valid axes.
"""

import jax
from jax.sharding import PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

AXIS_ORDER = ("data", "tensor")

RULES = {"batch": "data", "mlp": "tensor"}
BAD_RULES = {"embed": "fsdp_typo"}

GOOD_SPEC = P("data", "tensor")
GOOD_TUPLE_SPEC = P(("data", "tensor"), None)
BAD_AXIS_SPEC = P("data", "rows")
DUP_SPEC = P("data", "data")


def _body3(a, b, c):
    return jax.lax.psum(a, "data")


def build(mesh):
    good = shard_map(_body3, mesh=mesh,
                     in_specs=(P("data"), P(), P("tensor")),
                     out_specs=P("data"), check_vma=False)
    bad = shard_map(_body3, mesh=mesh,
                    in_specs=(P("data"), P()),
                    out_specs=P("data"), check_vma=False)
    return good, bad


def make_specs(rules):
    ok = rules.spec(("batch", "mlp"))
    bad = rules.spec(("batch", "typo_axis"))
    return ok, bad


def override(rules):
    ok = rules.with_overrides(batch="tensor")
    bad = rules.with_overrides(batch="ghost")
    return ok, bad
