"""R14 fixture: spans must be context-managed outside observability."""
from ray_tpu import observability
from ray_tpu.observability import span


def leaky():
    s = observability.span("fixture.leak", cat="fixture")
    s.__enter__()
    return s


def leaky_bare_import():
    return span("fixture.leak2")


def clean():
    with observability.span("fixture.clean", cat="fixture"):
        pass
    with span("fixture.clean2") as s:
        return s.trace_id
