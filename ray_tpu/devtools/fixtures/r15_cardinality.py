"""R15 fixture: metric tags must not carry unbounded runtime values."""
from ray_tpu.util import metrics

_counter = metrics.Counter("fixture_requests", "fixture")
_gauge = metrics.Gauge("fixture_state", "fixture")


def unbounded_hex(oid):
    _counter.inc(tags={"object_id": oid.hex()})


def unbounded_name(task_id, peer_addr):
    _gauge.set(1.0, tags={"task": task_id, "peer": peer_addr})


def unbounded_fstring(trace_id):
    _counter.inc(tags={"trace": f"trace-{trace_id}"})


def unbounded_default_tags(node_id):
    _gauge.set_default_tags({"node": node_id.hex()})


def allowed_small_cluster(peer):
    # raylint: allow(metrics-cardinality) bounded by cluster size
    _counter.inc(tags={"peer": peer})


def clean(route):
    _counter.inc(tags={"route": "/a", "method": "GET"})
    _gauge.set(0.0, tags={"phase": route})
