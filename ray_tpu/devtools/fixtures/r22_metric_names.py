"""R22 fixture: metric-name registry conformance.

Positive cases: ``bad_typo`` misspells a declared perf histogram,
``bad_adhoc`` invents an undeclared family, ``bad_category`` /
``bad_interval`` misspell goodput ledger categories.  Clean twins:
declared names in ``good``, a variable-valued name (dynamic, skipped),
and an unrelated object's own ``.observe()`` method.
"""

from ray_tpu.observability import goodput, perf


def bad_typo(ms):
    perf.observe("task.exeucte", ms)


def bad_adhoc(ms):
    perf.observe("myfeature.latency", ms)


def bad_category(s):
    goodput.account("checkpoint_stall", s)


def bad_interval():
    with goodput.interval("compile_wait"):
        pass


def good(ms, s, name):
    perf.observe("task.execute", ms)
    goodput.account("ckpt_stall", s)
    with goodput.interval("data_wait"):
        pass
    perf.observe(name, ms)  # dynamic: statically unverifiable, skipped


class _OwnHistogram:
    def observe(self, value):
        self.value = value


def good_other(h, v):
    h.observe(v)  # not the perf plane: out of scope
