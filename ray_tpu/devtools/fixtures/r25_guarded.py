"""R25 fixture: both directions of guarded-by enforcement — a declared
field touched without its lock (positive a), a consistently-locked
multi-thread field missing its declaration (positive b), and a fully
declared-and-locked class that satisfies the contract (negative)."""
import threading


class LeakyBox:
    """Positive (a): ``peek`` reads the declared field lock-free."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # raylint: guarded-by(self._lock)
        self._t = threading.Thread(target=self._fill, daemon=True)
        self._t.start()

    def _fill(self):
        with self._lock:
            self._items.append(1)

    def peek(self) -> int:
        return len(self._items)


class QuietBox:
    """Positive (b): every access locks, two thread contexts reach the
    field, but no declaration records the convention."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._t = threading.Thread(target=self._fill, daemon=True)
        self._t.start()

    def _fill(self):
        with self._lock:
            self._items.append(1)

    def peek(self) -> int:
        with self._lock:
            return len(self._items)


class SealedBox:
    """Negative: declared, and every access site holds the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # raylint: guarded-by(self._lock)
        self._t = threading.Thread(target=self._fill, daemon=True)
        self._t.start()

    def _fill(self):
        with self._lock:
            self._items.append(1)

    def peek(self) -> int:
        with self._lock:
            return len(self._items)


def drain(a: LeakyBox, b: QuietBox, c: SealedBox) -> int:
    return a.peek() + b.peek() + c.peek()
