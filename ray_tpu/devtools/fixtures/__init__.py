"""Self-check fixture corpus for raylint (``--self-check``).

Each ``r1N_*.py`` file carries one positive and one negative case for a
whole-program rule (R10-R13); ``expected.json`` freezes the exact
findings the corpus must round-trip. The directory is excluded from
normal lint walks (see ``LintEngine._iter_files``) and is only analyzed
when rooted here explicitly — these files are never imported at runtime.
"""
