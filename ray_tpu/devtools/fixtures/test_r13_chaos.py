"""R13 fixture: the tests/ half of the chaos-point closure."""
from ray_tpu import chaos


def test_exercises_points():
    # negative: this spec makes fixture.point.tested "exercised"
    chaos.configure(3, "fixture.point.tested@1=error")
    # positive: no runtime inject declares this point
    spec = "fixture.point.ghost@1=drop"
    return spec
