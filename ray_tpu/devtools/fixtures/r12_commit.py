"""R12 fixture: a checkpoint-commit barrier dominated by a rank branch —
the regression shape for the rank-divergent commit deadlock."""


def barrier():
    """Stand-in collective; R12 keys on the callee NAME."""


def divergent_commit(rank, state):
    if rank == 0:
        _commit(state)
        barrier()


def uniform_commit(rank, state):
    # negative: the branch is rank-dependent but every rank still reaches
    # the same collective sequence afterwards
    if rank == 0:
        _commit(state)
    barrier()


def _commit(state):
    state["committed"] = True
