"""R12 fixture: a checkpoint-commit barrier dominated by a rank branch —
the regression shape for the rank-divergent commit deadlock."""


def barrier():
    """Stand-in collective; R12 keys on the callee NAME."""


def divergent_commit(rank, state):
    if rank == 0:
        _commit(state)
        barrier()


def uniform_commit(rank, state):
    # negative: the branch is rank-dependent but every rank still reaches
    # the same collective sequence afterwards
    if rank == 0:
        _commit(state)
    barrier()


def _commit(state):
    state["committed"] = True


class CollectiveConfig:
    """Stand-in; R12's config arm keys on the callee NAME."""

    def __init__(self, compression="none", quant_block_bytes=256):
        self.compression = compression
        self.quant_block_bytes = quant_block_bytes


def divergent_config(rank):
    # positive: a per-rank compression scheme folds into the rendezvous
    # fingerprint and diverges at the group's first op
    return CollectiveConfig(compression="q8" if rank == 0 else "none")


def uniform_config():
    # negative: one literal config for the whole group
    return CollectiveConfig(compression="q8", quant_block_bytes=512)
