"""R18 fixture: protocol vocabulary, reply discipline, node lifecycle.

Positive cases: ``send_orphan`` ships a method no dispatcher handles,
``dispatch`` guards a method nothing sends, ``handler_no_reply`` can
complete without replying, and ``promote_drained`` writes a transition
the declared NODE_LIFECYCLE table does not admit.  ``send_echo`` /
``dispatch``'s ECHO arm / ``demote_draining`` are the clean twins.
"""


class pb:
    ORPHAN_SEND = 1
    DEAD_ARM = 2
    ECHO = 3


def send_orphan(client):
    client.call(pb.ORPHAN_SEND, b"")


def send_echo(client):
    client.call(pb.ECHO, b"")


def dispatch(env, ctx):
    if env.method == pb.DEAD_ARM:
        ctx.reply(b"")
    elif env.method == pb.ECHO:
        ctx.reply(b"pong")
    else:
        ctx.reply_error("unknown method")


def handler_no_reply(env, ctx):
    if env.method == pb.ECHO:
        ctx.reply(b"pong")


def promote_drained(node):
    if node.state == "DRAINED":
        node.state = "ALIVE"


def demote_draining(node):
    if node.state == "DRAINING":
        node.state = "DRAINED"
