"""R11 fixture (half 1): acquires ORDER_LOCK, then r11_b.PEER_LOCK via a
cross-module call — r11_b closes the cycle in the other direction."""
import threading

from fixtures import r11_b

ORDER_LOCK = threading.Lock()


def hold_a_then_b():
    with ORDER_LOCK:
        r11_b.hold_b()


def hold_a():
    with ORDER_LOCK:
        pass
