"""R10 fixture: an async handler reaches time.sleep through sync helpers."""
import threading
import time


def _backoff():
    time.sleep(0.2)


def _relay():
    _backoff()


async def handle():
    _relay()


async def spawned_ok():
    # negative: spawn edge — the sleep runs on its own thread, the event
    # loop never blocks
    threading.Thread(target=_backoff).start()


async def dynamic_ok(callback):
    # negative: unresolvable dynamic call must degrade to "unknown"
    callback()
