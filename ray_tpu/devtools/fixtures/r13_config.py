"""R13 fixture: config-knob and chaos-point drift (runtime half)."""
from ray_tpu import chaos
from ray_tpu._private.config import _config

_config.define("fixture_live_knob", int, 1, "read below: not dead")
_config.define("fixture_dead_knob", int, 2, "never read anywhere")


def read_knobs():
    a = _config.get("fixture_live_knob")
    b = _config.get("fixture_missing_knob")
    return a + b


def fault_paths():
    chaos.inject("fixture.point.tested")
    chaos.inject("fixture.point.untested")
