"""R11 fixture (half 2): the reverse acquisition order, plus a negative
case where the peer lock is only reached across a thread-spawn edge."""
import threading

from fixtures import r11_a

PEER_LOCK = threading.Lock()


def hold_b():
    with PEER_LOCK:
        pass


def hold_b_then_a():
    with PEER_LOCK:
        r11_a.hold_a()


def spawn_ok():
    # negative: locks are not held across a spawn edge — the new thread
    # starts with an empty hold set, so this creates no A->B edge
    t = threading.Thread(target=r11_a.hold_a, daemon=True)
    with PEER_LOCK:
        t.start()
