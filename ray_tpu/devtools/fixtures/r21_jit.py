"""R21 fixture: jit compile-cache stability.

Positive cases: ``loop_jit`` constructs inside a loop, ``per_call`` /
``immediate`` construct-and-invoke per call, ``bad_static`` /
``bad_shape`` / ``bad_decorated_call`` feed unhashable or
shape-varying values to ``static_argnums`` positions, ``bad_donate``
reads a donated buffer after the call, and ``bad_scalar`` routes a raw
``len(...)`` into a jitted call.  Clean twins: the module-level
``_CACHED`` construct, ``Model.__init__``'s attribute store,
``Model.good``'s rebinding of the donated arg, and ``padded_scalar``
bucketing through ``pad_items`` first.
"""

import functools

import jax


def pad_items(items, buckets):
    return items


def _impl(state, k):
    return state


_CACHED = jax.jit(_impl, static_argnums=(1,))


def loop_jit(xs):
    out = []
    for x in xs:
        f = jax.jit(_impl, static_argnums=(1,))
        out.append(f(x, 1))
    return out


def per_call(x):
    f = jax.jit(_impl, static_argnums=(1,))
    return f(x, 1)


def immediate(x):
    return jax.jit(_impl, static_argnums=(1,))(x, 1)


@functools.partial(jax.jit, static_argnums=(1,))
def decorated_step(state, k):
    return state


def bad_decorated_call(state):
    return decorated_step(state, {"a": 1})


class Model:
    def __init__(self):
        self._step = jax.jit(_impl, static_argnums=(1,),
                             donate_argnums=(0,))

    def good(self, state):
        state = self._step(state, 4)
        return state

    def bad_static(self, state):
        return self._step(state, [1, 2])

    def bad_shape(self, state, x):
        return self._step(state, x.shape)

    def bad_donate(self, state):
        out = self._step(state, 4)
        return out, state

    def bad_scalar(self, state, items):
        return self._step(state, len(items))

    def padded_scalar(self, state, items):
        items = pad_items(items, (8, 16))
        return self._step(state, len(items))
