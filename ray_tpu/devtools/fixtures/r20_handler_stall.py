"""R20 fixture: unbounded blocking reachable from an RPC dispatch arm.

Positive case: the WORK arm reaches ``helper``'s bare ``ev.wait()`` —
a stalled handler pins a dispatch thread for every caller.  Clean
twins: the BOUNDED arm goes through ``scoped_helper``, whose
``deadline`` parameter is the budget fact that suppresses R20 (the
naked wait under it is R17's jurisdiction, allowed in place here), and
``capped_helper`` passes an explicit timeout so nothing is naked.
"""


class pb:
    WORK = 10
    BOUNDED = 11


def helper(ev):
    ev.wait()


def scoped_helper(ev, deadline):
    # raylint: allow(deadline-drop) fixture: the deadline fact itself is R20's suppression under test
    ev.wait()


def capped_helper(ev):
    ev.wait(1.0)


def dispatch(env, ctx, ev):
    if env.method == pb.WORK:
        helper(ev)
        ctx.reply(b"")
    elif env.method == pb.BOUNDED:
        scoped_helper(ev, 1.0)
        capped_helper(ev)
        ctx.reply(b"")
    else:
        ctx.reply_error("unknown method")


def send_work(client):
    client.call(pb.WORK, b"")


def send_bounded(client):
    client.call(pb.BOUNDED, b"")
