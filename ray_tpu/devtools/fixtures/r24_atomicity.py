"""R24 fixture: a read-modify-write whose halves each take the lock but
release it in between (positive), plus the widened-critical-section
shape that must stay quiet (negative)."""
import threading


class SplitQuota:
    """Positive: ``bump_stale`` snapshots under the lock, drops it, then
    writes back under a second acquisition — the grower thread can
    interleave and its increment is lost."""

    def __init__(self):
        self._lock = threading.Lock()
        self._used = 0  # raylint: guarded-by(self._lock)
        self._t = threading.Thread(target=self._grow, daemon=True)
        self._t.start()

    def _grow(self):
        with self._lock:
            self._used += 1

    def bump_stale(self):
        with self._lock:
            n = self._used
        with self._lock:
            self._used = n + 1


class WholeQuota:
    """Negative: one critical section covers the read and the dependent
    write, so no interleaving window exists."""

    def __init__(self):
        self._lock = threading.Lock()
        self._used = 0  # raylint: guarded-by(self._lock)
        self._t = threading.Thread(target=self._grow, daemon=True)
        self._t.start()

    def _grow(self):
        with self._lock:
            self._used += 1

    def bump(self):
        with self._lock:
            n = self._used
            self._used = n + 1


def drive(a: SplitQuota, b: WholeQuota) -> None:
    a.bump_stale()
    b.bump()
