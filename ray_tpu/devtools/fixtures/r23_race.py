"""R23 fixture: unsynchronized cross-thread field access (positive) next
to the three suppression shapes the rule promises to honor (negative)."""
import threading


class RaceyGauge:
    """Positive: the drain thread writes ``level`` with no lock while
    main-context readers take unlocked snapshots."""

    def __init__(self):
        self.level = 0
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def _drain(self):
        self.level = 1

    def read_level(self):
        return self.level


class GuardedGauge:
    """Negative: declared and consistently locked — R25 owns the
    contract, so R23 stays quiet."""

    def __init__(self):
        self._lock = threading.Lock()
        self.level = 0  # raylint: guarded-by(self._lock)
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def _drain(self):
        with self._lock:
            self.level = 1

    def read_level(self):
        with self._lock:
            return self.level


class FlagStop:
    """Negative: bool fast-path flag — a pointer-sized constant store
    cannot tear, so the stop-flag idiom is exempt."""

    def __init__(self):
        self._stop = False
        self._t = threading.Thread(target=self._step, daemon=True)
        self._t.start()

    def _step(self):
        if not self._stop:
            self._work()

    def _work(self):
        pass

    def stop(self):
        self._stop = True


class Handoff:
    """Negative: single-writer-before-spawn — every write happens before
    ``Thread.start()`` publishes the object to the worker."""

    def __init__(self):
        self.payload = []
        self.payload.append(1)
        self._t = threading.Thread(target=self._consume, daemon=True)
        self._t.start()

    def _consume(self):
        return list(self.payload)


def poll(g: RaceyGauge, h: Handoff) -> int:
    return g.read_level() + len(h.payload)
