"""Runtime lock-order watchdog.

The runtime's threads-as-workers stance means deadlock safety rests on
every pair of locks being taken in one consistent order across all
threads.  This module checks that *empirically*: when installed it wraps
``threading.Lock``/``threading.RLock`` creation (for callers inside the
ray_tpu package and its test suite) in a proxy that records, per thread,
which locks are held when another is acquired.  Those observations form a
directed graph over lock *creation sites*; a cycle in the graph is a
potential deadlock even if no run has hit it yet.  It also flags holds
longer than a threshold — long holds under the device-owner daemon stall
every worker thread behind them.

Activation::

    RAY_TPU_LOCKWATCH=1 python -m pytest tests/ ...          # any workload
    RAY_TPU_LOCKWATCH_OUT=/tmp/lockwatch.json ...            # JSON report
    RAY_TPU_LOCKWATCH_HOLD_S=0.5 ...                         # hold threshold
    RAY_TPU_LOCKWATCH_ALL=1 ...                              # wrap every caller

``ray_tpu/__init__`` installs the watchdog before importing any submodule
when ``RAY_TPU_LOCKWATCH`` is set, so module-level locks are wrapped too.
At process exit a one-line summary goes to stderr (details when cycles
were seen) and, if ``RAY_TPU_LOCKWATCH_OUT`` is set, the full report is
written there as JSON.

Two cycle granularities:

- **cross-site**: lock site A was held while acquiring site B somewhere,
  and B while acquiring A somewhere else — the classic ABBA.
- **same-site**: two *instances* created at the same line were each held
  while acquiring the other.  Site-level analysis cannot order these, so
  the proxy tracks instance pairs; a consistent hierarchy (always parent
  before child) stays clean, an inversion is reported.

Unit-test surface: :func:`wrap` wraps a single lock with an explicit
name, no installation required.
"""

from __future__ import annotations

import _thread
import atexit
import functools
import itertools
import json
import os
import re
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["install", "uninstall", "installed", "wrap", "Lock", "RLock",
           "report", "cycles", "reset", "format_cycle", "format_guard",
           "guard", "guard_class", "level"]


def format_cycle(kind: str, sites) -> str:
    """Canonical one-line rendering of a lock-order cycle.

    Shared by the runtime exit report and raylint R11's static findings:
    both identify a cycle by its sorted participant sites, so
    ``CYCLE (site-order): A -> B`` from either tool names the same
    inversion and one allow/fix covers both."""
    return f"CYCLE ({kind}): " + " -> ".join(sites)


def format_guard(field: str, lock: str) -> str:
    """Canonical one-line rendering of a guarded-by violation.

    Shared by raylint R25's static findings and the level-2 runtime
    watchdog (``RAY_TPU_LOCKWATCH=2``): both name the field and its
    declared lock as ``Cls.attr``, so a static finding and a runtime
    report for the same field correlate by string equality on this
    prefix."""
    return f"guarded-by({lock}) violated: {field} accessed " \
           f"without {lock} held"


def level() -> int:
    """Numeric watchdog level from ``RAY_TPU_LOCKWATCH``: 0 = off,
    1 = lock-order graph, 2 = graph + guarded-field assertions (any
    non-integer truthy value reads as 1 for backward compatibility)."""
    raw = os.environ.get("RAY_TPU_LOCKWATCH", "")
    if not raw:
        return 0
    try:
        return int(raw)
    except ValueError:
        return 1

# raw primitives so the watchdog never traces itself
_graph_lock = _thread.allocate_lock()
_tls = threading.local()
_uid_counter = itertools.count(1)

_edges: Dict[Tuple[str, str], int] = {}            # (site_a, site_b) -> count  # raylint: guarded-by(_graph_lock)
_edge_threads: Dict[Tuple[str, str], str] = {}     # example thread name  # raylint: guarded-by(_graph_lock)
_same_site_pairs: Set[Tuple[int, int]] = set()     # (uid_held, uid_acquired)  # raylint: guarded-by(_graph_lock)
_same_site_of: Dict[Tuple[int, int], str] = {}     # pair -> site  # raylint: guarded-by(_graph_lock)
_long_holds: List[dict] = []  # raylint: guarded-by(_graph_lock)
_wrapped_count = 0
_guard_violations: List[dict] = []                 # level-2 findings
_guard_seen: Set[Tuple[str, str]] = set()          # (field, site) dedup
_guard_counter = itertools.count()                 # sampling clock

_orig_lock = None
_orig_rlock = None

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hold_threshold() -> float:
    try:
        return float(os.environ.get("RAY_TPU_LOCKWATCH_HOLD_S", "1.0"))
    except ValueError:
        return 1.0


def _held_stack() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _note_acquire(proxy: "_LockProxy") -> None:
    held = _held_stack()
    for entry in held:
        if entry[0] is proxy:       # RLock re-entry: no new ordering info
            entry[2] += 1
            return
    if held:
        with _graph_lock:
            for other, _, _ in held:
                if other._site != proxy._site:
                    key = (other._site, proxy._site)
                    _edges[key] = _edges.get(key, 0) + 1
                    _edge_threads.setdefault(
                        key, threading.current_thread().name)
                else:
                    pair = (other._uid, proxy._uid)
                    _same_site_pairs.add(pair)
                    _same_site_of[pair] = proxy._site
    held.append([proxy, time.monotonic(), 1])


def _note_release(proxy: "_LockProxy", full: bool = False) -> None:
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is proxy:
            held[i][2] -= 1
            if full or held[i][2] <= 0:
                dt = time.monotonic() - held[i][1]
                del held[i]
                if dt > _hold_threshold():
                    with _graph_lock:
                        _long_holds.append({
                            "site": proxy._site,
                            "seconds": round(dt, 3),
                            "thread": threading.current_thread().name,
                        })
            return
    # release of a lock this thread never acquired (hand-off patterns on
    # primitive locks): nothing to unwind


class _LockProxy:
    """Wraps a primitive lock; mirrors its API, records ordering."""

    __slots__ = ("_inner", "_site", "_uid", "__weakref__")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site
        self._uid = next(_uid_counter)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self)
        return ok

    def release(self) -> None:
        _note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockwatch {type(self._inner).__name__} {self._site}>"


class _RLockProxy(_LockProxy):
    """RLock flavour: also speaks ``Condition``'s private protocol so a
    ``threading.Condition`` built on a wrapped RLock keeps working (and
    keeps the held-stack honest across ``wait()``)."""

    __slots__ = ()

    def _release_save(self):
        _note_release(self, full=True)     # wait() drops all recursion levels
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        _note_acquire(self)

    def _is_owned(self):
        return self._inner._is_owned()


def wrap(lock=None, name: Optional[str] = None):
    """Wrap one lock explicitly (tests, ad-hoc probes).

    ``lock`` defaults to a fresh primitive lock; ``name`` defaults to the
    caller's ``file:line`` site.
    """
    global _wrapped_count
    if lock is None:
        lock = (_orig_lock or _thread.allocate_lock)()
    site = name or _caller_site(2)
    with _graph_lock:
        _wrapped_count += 1
    if hasattr(lock, "_is_owned") or "RLock" in type(lock).__name__:
        return _RLockProxy(lock, site)
    return _LockProxy(lock, site)


def _caller_site(depth: int) -> str:
    frame = sys._getframe(depth)
    path = frame.f_code.co_filename
    rel = os.path.basename(os.path.dirname(path)) + "/" + os.path.basename(path)
    return f"{rel}:{frame.f_lineno}"


def _should_wrap(filename: str) -> bool:
    if os.environ.get("RAY_TPU_LOCKWATCH_ALL"):
        return True
    norm = filename.replace(os.sep, "/")
    return filename.startswith(_PKG_ROOT) or "/tests/" in norm


def Lock():
    """Factory installed over ``threading.Lock``."""
    global _wrapped_count
    inner = (_orig_lock or _thread.allocate_lock)()
    frame = sys._getframe(1)
    if not _should_wrap(frame.f_code.co_filename):
        return inner
    with _graph_lock:
        _wrapped_count += 1
    return _LockProxy(inner, _caller_site(2))


def RLock():
    """Factory installed over ``threading.RLock``."""
    global _wrapped_count
    inner = (_orig_rlock or threading._PyRLock)()  # type: ignore[attr-defined]
    frame = sys._getframe(1)
    if not _should_wrap(frame.f_code.co_filename):
        return inner
    with _graph_lock:
        _wrapped_count += 1
    return _RLockProxy(inner, _caller_site(2))


def installed() -> bool:
    return _orig_lock is not None


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` with recording factories.

    Locks created by callers outside the ray_tpu package and its tests
    are returned unwrapped (stdlib and third-party internals keep their
    raw primitives) unless ``RAY_TPU_LOCKWATCH_ALL`` is set.
    """
    global _orig_lock, _orig_rlock
    if installed():
        return
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock
    threading.Lock = Lock
    threading.RLock = RLock
    atexit.register(_exit_report)


def uninstall() -> None:
    global _orig_lock, _orig_rlock
    if not installed():
        return
    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    _orig_lock = _orig_rlock = None
    atexit.unregister(_exit_report)


# -- RPC boundary pseudo-sites ---------------------------------------------
#
# Runtime counterpart to raylint R19's lock-held-across-RPC arm.  A
# synchronous RPC wait and a handler execution are modeled as pseudo-lock
# sites named ``rpc:<METHOD>``: a wrapped lock held across a blocking
# ``call()`` records the order edge ``lock-site -> rpc:M``, and a lock
# the M handler takes while running records ``rpc:M -> lock-site`` — two
# peers doing both close a ``CYCLE (site-order)`` over exactly the sites
# R19 names statically, so one fix/allow covers both reports.

def rpc_client_wait(site: str) -> None:
    """This thread is about to block on a synchronous RPC (*site* is
    ``rpc:<METHOD>``); order every currently-held wrapped lock before it."""
    held = _held_stack()
    if not held:
        return
    with _graph_lock:
        for other, _, _ in held:
            if other._site != site:
                key = (other._site, site)
                _edges[key] = _edges.get(key, 0) + 1
                _edge_threads.setdefault(
                    key, threading.current_thread().name)


def rpc_handler_enter(site: str) -> "_LockProxy":
    """A handler for *site* (``rpc:<METHOD>``) starts on this thread:
    push a pseudo-lock so locks it acquires order after the method.
    Returns a token for :func:`rpc_handler_exit`."""
    proxy = _LockProxy((_orig_lock or _thread.allocate_lock)(), site)
    _note_acquire(proxy)
    return proxy


def rpc_handler_exit(token: "_LockProxy") -> None:
    _note_release(token, full=True)


# -- guarded-field watchdog (level 2) ----------------------------------------
#
# Runtime mirror of raylint R25: at RAY_TPU_LOCKWATCH=2 the
# :func:`guard` class decorator turns every field declared with a
# ``# raylint: guarded-by(...)`` comment into a checking descriptor that
# samples get/set and asserts the declared lock is held by the accessing
# thread.  Violations print at exit in :func:`format_guard`'s one-line
# format — the same string R25 embeds in its static findings — so a live
# report and a static finding for the same field correlate directly.
# Below level 2 the decorator is an exact no-op (zero import-time and
# zero per-access cost).

_GUARD_DECL_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=#\n]+)?=[^#\n]*#\s*raylint:\s*"
    r"guarded-by\(([^)]+)\)")


def _guard_sample() -> int:
    """Check 1 in N guarded accesses (``RAY_TPU_LOCKWATCH_SAMPLE``,
    default 1 = every access)."""
    try:
        return max(1, int(os.environ.get("RAY_TPU_LOCKWATCH_SAMPLE", "1")))
    except ValueError:
        return 1


def _lock_held_here(lock) -> Optional[bool]:
    """Best-effort: does the *current thread* hold *lock*?  None when the
    primitive cannot answer.  A raw (unwrapped) Lock cannot attribute its
    owner, so ``locked()`` under-reports violations rather than inventing
    one while another thread legitimately holds it."""
    if isinstance(lock, _RLockProxy):
        return bool(lock._is_owned())
    if isinstance(lock, _LockProxy):
        return any(entry[0] is lock for entry in _held_stack())
    if hasattr(lock, "_is_owned"):
        try:
            return bool(lock._is_owned())
        except Exception:  # raylint: allow(swallow) foreign lock type; unknown ownership reported as None
            return None
    if hasattr(lock, "locked"):
        try:
            return bool(lock.locked())
        except Exception:  # raylint: allow(swallow) foreign lock type; unknown ownership reported as None
            return None
    return None


class _GuardedField:
    """Data descriptor over one declared field: get/set store through the
    instance ``__dict__`` and (sampled) assert the declared lock is held.
    Checks are armed only after ``__init__`` completes — construction
    writes touch an instance no other thread can see yet, matching the
    static rule's fresh-instance exemption."""

    __slots__ = ("_attr", "_field", "_lock_attr", "_lock_global",
                 "_lock_disp", "_module")

    def __init__(self, cls_name: str, module: str, attr: str,
                 lock_text: str):
        self._attr = attr
        self._field = f"{cls_name}.{attr}"
        self._module = module
        lock_text = lock_text.strip()
        if lock_text.startswith("self."):
            self._lock_attr: Optional[str] = lock_text[5:]
            self._lock_global: Optional[str] = None
            self._lock_disp = f"{cls_name}.{self._lock_attr}"
        else:
            self._lock_attr = None
            self._lock_global = lock_text.rsplit(".", 1)[-1]
            self._lock_disp = lock_text

    def _resolve_lock(self, obj):
        if self._lock_attr is not None:
            return obj.__dict__.get(self._lock_attr)
        mod = sys.modules.get(self._module)
        return getattr(mod, self._lock_global, None) \
            if mod is not None else None

    def _check(self, obj) -> None:
        if not obj.__dict__.get("_lockwatch_guard_ready"):
            return
        if next(_guard_counter) % _guard_sample():
            return
        lock = self._resolve_lock(obj)
        if lock is None:
            return
        if _lock_held_here(lock) is not False:
            return
        site = _caller_site(3)
        dedup = (self._field, site)
        with _graph_lock:
            if dedup in _guard_seen:
                return
            _guard_seen.add(dedup)
            _guard_violations.append({
                "field": self._field, "lock": self._lock_disp,
                "site": site,
                "thread": threading.current_thread().name})

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj)
        try:
            return obj.__dict__[self._attr]
        except KeyError:
            raise AttributeError(self._attr) from None

    def __set__(self, obj, value) -> None:
        self._check(obj)
        obj.__dict__[self._attr] = value

    def __delete__(self, obj) -> None:
        obj.__dict__.pop(self._attr, None)


def guard_class(cls):
    """Instrument *cls* unconditionally (unit-test surface — the
    level-gated entry point is :func:`guard`): every field its source
    declares with ``# raylint: guarded-by(...)`` becomes a checking
    :class:`_GuardedField`, and ``__init__`` is wrapped to arm the checks
    once construction finishes."""
    import inspect
    try:
        src = inspect.getsource(cls)
    except (OSError, TypeError):
        return cls
    decls: Dict[str, str] = {}
    for line in src.splitlines():
        m = _GUARD_DECL_RE.search(line)
        if m:
            decls.setdefault(m.group(1), m.group(2))
    if not decls:
        return cls
    for attr, lock_text in decls.items():
        setattr(cls, attr, _GuardedField(cls.__name__, cls.__module__,
                                         attr, lock_text))
    orig_init = cls.__init__

    @functools.wraps(orig_init)
    def _armed_init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        self.__dict__["_lockwatch_guard_ready"] = True

    cls.__init__ = _armed_init
    return cls


def guard(cls):
    """Class decorator: at ``RAY_TPU_LOCKWATCH=2`` instrument the class's
    guarded-by-declared fields (see :func:`guard_class`); below level 2,
    return the class untouched."""
    if level() < 2:
        return cls
    return guard_class(cls)


def guard_violations() -> List[dict]:
    with _graph_lock:
        return list(_guard_violations)


def reset() -> None:
    """Clear all recorded observations (keeps installation state)."""
    global _wrapped_count
    with _graph_lock:
        _edges.clear()
        _edge_threads.clear()
        _same_site_pairs.clear()
        _same_site_of.clear()
        _long_holds.clear()
        _guard_violations.clear()
        _guard_seen.clear()
        _wrapped_count = 0


def _sccs(nodes: List[str], succ: Dict[str, List[str]]) -> List[List[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = itertools.count()

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = next(counter)
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = next(counter)
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(succ.get(nxt, ()))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


def cycles() -> List[dict]:
    """Cycles in the observed lock-order graph (potential deadlocks)."""
    with _graph_lock:
        edge_keys = list(_edges)
        same_pairs = set(_same_site_pairs)
        same_of = dict(_same_site_of)
    succ: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in edge_keys:
        succ.setdefault(a, []).append(b)
        nodes.update((a, b))
    found: List[dict] = []
    for comp in _sccs(sorted(nodes), succ):
        if len(comp) > 1:
            found.append({"kind": "site-order", "sites": sorted(comp)})
    reported: Set[Tuple[int, int]] = set()
    for a, b in same_pairs:
        if (b, a) in same_pairs and (b, a) not in reported:
            reported.add((a, b))
            found.append({"kind": "same-site-inversion",
                          "sites": [same_of[(a, b)]]})
    return found


def report() -> dict:
    with _graph_lock:
        edges = [{"from": a, "to": b, "count": n,
                  "thread": _edge_threads.get((a, b), "")}
                 for (a, b), n in sorted(_edges.items())]
        holds = list(_long_holds)
        wrapped = _wrapped_count
        guards = [dict(v) for v in _guard_violations]
    return {"wrapped_locks": wrapped, "edges": edges, "cycles": cycles(),
            "long_holds": holds, "guard_violations": guards}


def _exit_report() -> None:
    rep = report()
    n_cycles = len(rep["cycles"])
    n_guard = len(rep["guard_violations"])
    print(f"LOCKWATCH: {rep['wrapped_locks']} locks wrapped, "
          f"{len(rep['edges'])} order edges, {n_cycles} cycles, "
          f"{len(rep['long_holds'])} long holds, "
          f"{n_guard} guard violations", file=sys.stderr)
    for v in rep["guard_violations"]:
        print("LOCKWATCH R25 " + format_guard(v["field"], v["lock"])
              + f" at {v['site']} [{v['thread']}]", file=sys.stderr)
    if n_cycles:
        for cyc in rep["cycles"]:
            print("LOCKWATCH " + format_cycle(cyc["kind"], cyc["sites"]),
                  file=sys.stderr)
        for e in rep["edges"]:
            print(f"LOCKWATCH edge: {e['from']} -> {e['to']} "
                  f"x{e['count']} [{e['thread']}]", file=sys.stderr)
    out = os.environ.get("RAY_TPU_LOCKWATCH_OUT")
    if out:
        try:
            with open(out, "w", encoding="utf-8") as f:
                json.dump(rep, f, indent=2)
        except OSError as e:
            print(f"LOCKWATCH: cannot write {out}: {e}", file=sys.stderr)
