"""Whole-program symbol table, call graph, and interprocedural summaries.

The per-file rules in :mod:`ray_tpu.devtools.linter` see one parse tree at
a time; the invariants that actually deadlock TPU clusters live *between*
functions: a blocking primitive three calls below an ``async def``, a lock
taken in one method and another lock taken in a callee two files away, a
collective dominated by a rank branch whose body lives in a helper.  This
module builds the shared substrate those interprocedural rules (R10-R13)
run on:

1. **Symbol table** — every module under the lint roots, its top-level
   functions, classes (with base-class links), and import aliases
   (``import a.b as c`` and ``from m import f as g``, including relative
   imports resolved against the package).
2. **Call graph** — one :class:`CallSite` per call expression, resolved
   module-level: plain names through import chains and re-exports,
   ``self.method`` through the class and its project-resolvable bases
   (MRO walk), ``cls.method``, ``super().method``, ``self.attr.method``
   through light attribute-type inference (``self.attr = ClassName(...)``
   anywhere in the class), and local-variable types
   (``v = ClassName(...); v.method()``).  Thread/async entry points are
   classified as their own edge kinds so dataflow can distinguish "runs
   here, now" from "runs on another thread" from "runs on the event loop
   later".
3. **Per-function summaries** — direct blocking primitives, lock
   acquisitions (``with``-statements over lockish expressions, with the
   lexically-held set at each acquisition *and* at each outgoing call),
   and collective/barrier calls.  Rules compose these into transitive
   closures (see the fixpoint helpers at the bottom).

Soundness stance: the resolver is deliberately *under*-approximate.  A
call it cannot resolve degrades to ``target=None`` ("unknown") and simply
contributes no edges — rules built on the graph can therefore miss
findings through dynamic dispatch, but never invent a path that does not
exist.  That is the right polarity for a lint gate that fails CI.

Edge kinds:

===========  ==========================================================
kind         meaning
===========  ==========================================================
``call``     ordinary synchronous call — callee runs on this thread,
             now, with the caller's locks held (also ``await f()``)
``loop``     ``asyncio.create_task``/``ensure_future`` — the coroutine
             runs on *this* event loop, later: event-loop blocking
             propagates, lock-held sets do not
``spawn``    ``threading.Thread(target=...)``, ``executor.submit``,
             ``loop.run_in_executor``, ``call_soon_threadsafe`` — runs
             on another thread: neither blocking nor held locks
             propagate across it
``rpc``      a protocol send site (``client.call(pb.M, ...)``) stitched
             to the dispatch arm that handles ``M`` on the peer — the
             callee runs in ANOTHER PROCESS: locks do not propagate
             (each process has its own instances), but a synchronous
             send blocks this thread until the remote handler replies
===========  ==========================================================

**Cross-process stitching** (:meth:`ProjectIndex._stitch_rpc`): the R18
send/handler extraction already names, for every ``pb.<METHOD>``, the
send sites and the ``elif method == pb.<METHOD>:`` dispatch arms.  The
stitch pass synthesizes one FunctionInfo per dispatch arm (qname
``mod:Class._handle_rpc[METHOD]``, body = the arm's statements, analyzed
like any function so ``self._helper`` calls resolve) and adds an
``rpc``-kind CallSite from every send site to every arm handling that
method.  ``transitive_paths`` can then witness paths that cross daemon
boundaries.  The same under-approximation stance applies: a dispatcher
is only recognized when the dispatched expression provably comes from an
RpcContext-style parameter (``ctx.method`` or a local assigned from it),
and an unmatched method contributes no edges.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["ProjectIndex", "FunctionInfo", "ClassInfo", "ModuleInfo",
           "CallSite", "module_name_for", "FieldAccess", "FieldPlan",
           "field_display"]

_LOCKISH = re.compile(r"(^|[._])(lock|mutex|cv|cond|sem)", re.IGNORECASE)

#: Final attributes / names treated as collective or barrier operations
#: (R12).  Matched against the last segment of the called dotted name.
COLLECTIVE_NAMES = frozenset({
    "allreduce", "all_reduce", "allgather", "all_gather", "reducescatter",
    "reduce_scatter", "broadcast", "barrier", "all_to_all", "psum",
    "pmean", "pmax", "pmin", "ppermute",
})

#: Fully-resolved callables that act as cross-rank rendezvous even though
#: their names don't look like collectives: every rank must reach them the
#: same number of times (rank 0 gathers the other ranks' shard indexes in
#: the checkpoint commit barrier; ``session.report`` feeds it).
BARRIER_QNAMES = frozenset({
    "ray_tpu.checkpoint.engine:CheckpointEngine.save",
    "ray_tpu.train.session:report",
    "ray_tpu.train.session:_TrainSession.report",
})


def module_name_for(relpath: str) -> str:
    """Dotted module name for a lint-root-relative path.

    ``ray_tpu/_private/rpc.py`` -> ``ray_tpu._private.rpc``;
    ``ray_tpu/__init__.py`` -> ``ray_tpu``; ``bench.py`` -> ``bench``.
    """
    rel = relpath.replace(os.sep, "/").replace("\\", "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class CallSite:
    line: int
    raw: str                      # dotted text as written ("self.flush")
    target: Optional[str]         # resolved function qname, or None
    kind: str = "call"            # call | loop | spawn | rpc
    locks_held: Tuple[str, ...] = ()


@dataclass
class FunctionInfo:
    qname: str                    # "mod:func" or "mod:Class.method"
    module: str
    cls: Optional[str]
    name: str
    node: ast.AST
    ctx: object                   # linter.FileContext
    is_async: bool = False
    # "rpc-arm" for per-dispatch-arm functions synthesized by the stitch
    # pass; rules that enumerate ALL functions for direct facts skip these
    # (their statements belong to the real dispatcher too)
    synthetic: Optional[str] = None
    call_sites: List[CallSite] = field(default_factory=list)
    # call AST node id -> CallSite, for rules that re-walk statements
    site_by_node: Dict[int, CallSite] = field(default_factory=dict)
    # (line, description) of directly-invoked blocking primitives
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    # (lock_id, line, locks already held lexically)
    acquires: List[Tuple[str, int, Tuple[str, ...]]] = field(
        default_factory=list)
    # (line, collective name) invoked directly
    collectives: List[Tuple[int, str]] = field(default_factory=list)


@dataclass
class ClassInfo:
    qname: str                    # "mod:Class"
    module: str
    name: str
    bases: List[str] = field(default_factory=list)       # dotted, as written
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> cls qname


@dataclass
class FieldAccess:
    """One shared-attribute access site, expanded with the thread
    contexts that can reach it.  ``ctxs[name]`` is the *effective*
    lockset there: locks held lexically at the site, unioned with the
    locks every path from that context's root must hold on entry to the
    enclosing function (intersection over call paths — a must-analysis,
    so a lock only counts when it is provably held)."""
    key: str                       # "mod:Cls.attr" | "mod.name"
    rel: str                       # file, lint-root relative
    line: int
    mode: str                      # read | write | mutate
    fnq: str                       # enclosing function qname
    locks: Tuple[str, ...]         # lexically held at the site
    wconst: str                    # "flag" for True/False/None writes
    ctxs: Dict[str, frozenset] = field(default_factory=dict)


@dataclass
class FieldPlan:
    """Joined whole-program field-safety facts for R23-R25."""
    roots: Dict[str, Tuple[str, int, str]]        # ctx -> (rel, line, how)
    contexts: Dict[str, Dict[str, frozenset]]     # fnq -> ctx -> must-held
    accesses: Dict[str, List[FieldAccess]]        # key -> live sites
    guarded: Dict[str, Tuple[str, str, int]]      # key -> (lock, rel, line)
    splits: List[Tuple[str, str, int, int, str]]  # (fnq,key,rline,wline,kind)
    init_only: Set[str]                           # construction-only fns
    atomic_keys: Set[str]
    flag_keys: Set[str]                           # bool fast-path fields
    spawns_in: Dict[str, List[Tuple[str, int]]]   # fnq -> [(root, line)]


def field_display(key: str) -> str:
    """Human/runtime-correlatable name for a field key: strip the module
    qualifier from ``mod:Cls.attr`` so static R25 findings and lockwatch
    level-2 reports (which only know ``Cls.attr``) compare equal."""
    return key.split(":", 1)[1] if ":" in key else key


@dataclass
class ModuleInfo:
    name: str
    ctx: object
    is_package: bool = False
    imports: Dict[str, str] = field(default_factory=dict)    # local -> dotted
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)


class ProjectIndex:
    """Symbol table + resolved call graph over a set of FileContexts."""

    def __init__(self, ctxs: Iterable[object],
                 stitch_facts: Optional[Dict[str, dict]] = None,
                 field_facts: Optional[Dict[str, dict]] = None):
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.ctx_of: Dict[str, object] = {}      # relpath -> FileContext
        for ctx in ctxs:
            self._add_module(ctx)
        for mod in self.modules.values():
            self._collect_imports(mod)
        for cls in self.classes.values():
            self._infer_attr_types(cls)
        for fn in self.functions.values():
            self._analyze(fn)
        # cross-process edges: method -> arm qnames, plus one record per
        # send site (qname, line, method, sync, locks_held, arm targets).
        # ``stitch_facts`` replays per-file send/dispatcher discovery from
        # the incremental cache (entries are hash-validated by the caller).
        self.rpc_arms: Dict[str, List[str]] = {}
        self.rpc_sites: List[Tuple[str, int, str, bool,
                                   Tuple[str, ...], Tuple[str, ...]]] = []
        self.stitch_facts: Dict[str, dict] = {}
        self.stitch_hits = 0
        self._stitch_rpc(stitch_facts or {})
        # per-file field facts (R23-R25), built lazily by field_plan():
        # ``field_facts`` replays hash-validated entries from the cache
        self.field_facts: Dict[str, dict] = {}
        self.field_hits = 0
        self._field_cache: Dict[str, dict] = field_facts or {}
        self._plan: Optional[FieldPlan] = None

    # -- construction ------------------------------------------------------

    def _add_module(self, ctx) -> None:
        mod = ModuleInfo(module_name_for(ctx.relpath), ctx,
                         is_package=ctx.relpath.replace("\\", "/")
                         .endswith("__init__.py"))
        self.modules[mod.name] = mod
        self.ctx_of[ctx.relpath] = ctx

        def add_fn(node, cls: Optional[ClassInfo]):
            owner = f"{cls.name}." if cls else ""
            info = FunctionInfo(
                qname=f"{mod.name}:{owner}{node.name}", module=mod.name,
                cls=cls.name if cls else None, name=node.name, node=node,
                ctx=ctx, is_async=isinstance(node, ast.AsyncFunctionDef))
            # first definition wins (overloads/redefinitions are rare and
            # resolving to the first keeps the graph deterministic)
            self.functions.setdefault(info.qname, info)
            if cls is not None:
                cls.methods.setdefault(node.name, info)
            else:
                mod.functions.setdefault(node.name, info)
            return info

        def walk(node, cls: Optional[ClassInfo]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    cinfo = ClassInfo(qname=f"{mod.name}:{child.name}",
                                      module=mod.name, name=child.name,
                                      bases=[b for b in
                                             (_dotted(x) for x in child.bases)
                                             if b])
                    self.classes.setdefault(cinfo.qname, cinfo)
                    mod.classes.setdefault(child.name, cinfo)
                    walk(child, cinfo)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    add_fn(child, cls)
                    # nested defs are indexed under the same class scope so
                    # self.x inside them still resolves; their call sites
                    # stay separate from the parent's (pruned walk)
                    walk(child, cls)
                else:
                    walk(child, cls)

        walk(ctx.tree, None)

    def _collect_imports(self, mod: ModuleInfo) -> None:
        package = mod.name if mod.is_package else (
            mod.name.rsplit(".", 1)[0] if "." in mod.name else "")
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        mod.imports[alias.asname] = alias.name
                    else:
                        # "import a.b.c" binds "a"
                        mod.imports[alias.name.split(".")[0]] = \
                            alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    anchor = package
                    for _ in range(node.level - 1):
                        anchor = anchor.rsplit(".", 1)[0] if "." in anchor \
                            else ""
                    base = f"{anchor}.{base}".strip(".") if base else anchor
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mod.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}" if base else alias.name

    # -- resolution --------------------------------------------------------

    def _resolve_qualified(self, dotted: str,
                           depth: int = 0) -> Optional[str]:
        """Resolve an absolute dotted path to a symbol key.

        Returns a function qname (``mod:f`` / ``mod:C.m``), a class qname
        (``mod:C``), a module name, or None.  Follows re-exports through
        ``__init__`` modules with a depth guard.
        """
        if depth > 8:
            return None
        parts = dotted.split(".")
        for cut in range(len(parts), 0, -1):
            mod = self.modules.get(".".join(parts[:cut]))
            if mod is None:
                continue
            rest = parts[cut:]
            if not rest:
                return mod.name
            head, tail = rest[0], rest[1:]
            if head in mod.functions and not tail:
                return mod.functions[head].qname
            if head in mod.classes:
                cls = mod.classes[head]
                if not tail:
                    return cls.qname
                if len(tail) == 1:
                    m = self.lookup_method(cls, tail[0])
                    return m.qname if m else None
                return None
            if head in mod.imports:
                return self._resolve_qualified(
                    ".".join([mod.imports[head]] + tail), depth + 1)
            return None
        return None

    def resolve_name(self, mod: ModuleInfo, dotted: str) -> Optional[str]:
        """Resolve a dotted expression written in *mod*'s scope."""
        parts = dotted.split(".")
        head, tail = parts[0], parts[1:]
        if head in mod.functions and not tail:
            return mod.functions[head].qname
        if head in mod.classes:
            cls = mod.classes[head]
            if not tail:
                return cls.qname
            if len(tail) == 1:
                m = self.lookup_method(cls, tail[0])
                return m.qname if m else None
            return None
        if head in mod.imports:
            return self._resolve_qualified(
                ".".join([mod.imports[head]] + tail))
        return None

    def lookup_method(self, cls: ClassInfo, name: str,
                      _seen: Tuple[str, ...] = ()) -> Optional[FunctionInfo]:
        """Method lookup through the class and project-resolvable bases."""
        if cls.qname in _seen:
            return None
        if name in cls.methods:
            return cls.methods[name]
        mod = self.modules.get(cls.module)
        for base in cls.bases:
            key = self.resolve_name(mod, base) if mod else None
            binfo = self.classes.get(key) if key else None
            if binfo is not None:
                found = self.lookup_method(binfo, name,
                                           _seen + (cls.qname,))
                if found:
                    return found
        return None

    def _class_of(self, fn: FunctionInfo) -> Optional[ClassInfo]:
        return self.classes.get(f"{fn.module}:{fn.cls}") if fn.cls else None

    def _infer_attr_types(self, cls: ClassInfo) -> None:
        """``self.attr = ClassName(...)`` anywhere in the class types attr."""
        mod = self.modules.get(cls.module)
        for m in cls.methods.values():
            for node in ast.walk(m.node):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                ctor = _dotted(node.value.func)
                key = self.resolve_name(mod, ctor) if (ctor and mod) else None
                if key not in self.classes:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        cls.attr_types.setdefault(t.attr, key)

    # -- per-function analysis --------------------------------------------

    def _lock_identity(self, expr: ast.AST,
                       fn: FunctionInfo) -> Optional[str]:
        text = _dotted(expr)
        if not text or not _LOCKISH.search(text):
            return None
        if text.startswith("self."):
            # class-qualified, like R2 and lockwatch's per-site identity
            return f"{fn.cls or '?'}.{text[5:]}"
        if "." not in text:
            # bare module-global lock: qualify by module so same-named
            # globals in different files never merge into one node
            return f"{fn.module}.{text}"
        # module-alias attribute (``with b.LOCK: ...`` after ``from proj
        # import b``): rewrite to the defining module's node so it merges
        # with that module's own bare-name acquisitions
        parts = text.split(".")
        mod = self.modules.get(fn.module)
        if mod is not None and parts[0] in mod.imports:
            target = mod.imports[parts[0]]
            if target in self.modules:
                return ".".join([target] + parts[1:])
        return text

    def _resolve_call(self, fn: FunctionInfo, dn: Optional[str],
                      local_types: Dict[str, str]) -> Optional[str]:
        if not dn:
            return None
        mod = self.modules.get(fn.module)
        cls = self._class_of(fn)
        parts = dn.split(".")
        if parts[0] in ("self", "cls") and cls is not None:
            if len(parts) == 2:
                m = self.lookup_method(cls, parts[1])
                return m.qname if m else None
            if len(parts) == 3:
                tkey = cls.attr_types.get(parts[1])
                tcls = self.classes.get(tkey) if tkey else None
                if tcls is not None:
                    m = self.lookup_method(tcls, parts[2])
                    return m.qname if m else None
            return None
        if parts[0] in local_types and len(parts) == 2:
            tcls = self.classes.get(local_types[parts[0]])
            if tcls is not None:
                m = self.lookup_method(tcls, parts[1])
                return m.qname if m else None
            return None
        key = self.resolve_name(mod, dn) if mod else None
        if key in self.classes:
            # constructing a class: the synchronous work is __init__
            init = self.lookup_method(self.classes[key], "__init__")
            return init.qname if init else None
        if key in self.functions:
            return key
        return None

    def _blocking_reason(self, node: ast.Call, fn: FunctionInfo,
                         dn: Optional[str]) -> Optional[str]:
        ctx = fn.ctx
        if dn == "time.sleep" or (
                dn == "sleep" and
                getattr(ctx, "from_imports", {}).get("sleep") == "time"):
            return "blocking time.sleep()"
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            kwargs = {kw.arg for kw in node.keywords}
            if attr == "result" and not node.args and "timeout" not in kwargs:
                return "blocking Future.result() without timeout"
            if attr == "acquire" and _LOCKISH.search(
                    _dotted(node.func.value) or ""):
                if not node.args and not ({"timeout", "blocking"} & kwargs):
                    return "lock .acquire() with no timeout"
            if attr == "get" and _dotted(node.func.value) == "ray_tpu":
                return "blocking ray_tpu.get()"
        elif isinstance(node.func, ast.Name) and node.func.id == "get" and \
                getattr(ctx, "from_imports", {}).get(
                    "get", "").startswith("ray_tpu"):
            return "blocking ray_tpu.get()"
        return None

    def _analyze(self, fn: FunctionInfo) -> None:
        local_types: Dict[str, str] = {}
        mod = self.modules.get(fn.module)
        cls = self._class_of(fn)
        held: List[str] = []

        def add_site(node: ast.Call, target: Optional[str], kind: str,
                     raw: str) -> None:
            site = CallSite(line=node.lineno, raw=raw, target=target,
                            kind=kind, locks_held=tuple(held))
            fn.call_sites.append(site)
            fn.site_by_node[id(node)] = site

        def spawn_target(node: ast.Call) -> Optional[ast.AST]:
            dn = _dotted(node.func)
            if dn in ("threading.Thread", "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        return kw.value
                return None
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in ("submit", "call_soon_threadsafe"):
                    return node.args[0] if node.args else None
                if node.func.attr == "run_in_executor":
                    return node.args[1] if len(node.args) > 1 else None
            return None

        def handle_call(node: ast.Call) -> None:
            dn = _dotted(node.func)
            reason = self._blocking_reason(node, fn, dn)
            if reason is not None:
                fn.blocking.append((node.lineno, reason))
            last = (dn or "").rsplit(".", 1)[-1]
            target: Optional[str]
            if dn in ("asyncio.create_task", "asyncio.ensure_future",
                      "create_task", "ensure_future") and node.args and \
                    isinstance(node.args[0], ast.Call):
                inner = _dotted(node.args[0].func)
                target = self._resolve_call(fn, inner, local_types)
                add_site(node, target, "loop", inner or "<dynamic>")
                return
            st = spawn_target(node)
            if st is not None:
                sdn = _dotted(st)
                target = self._resolve_call(fn, sdn, local_types)
                add_site(node, target, "spawn", sdn or "<dynamic>")
                return
            # super().method() -> first base that defines it
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Call) and \
                    isinstance(node.func.value.func, ast.Name) and \
                    node.func.value.func.id == "super" and cls is not None:
                m = None
                for base in cls.bases:
                    key = self.resolve_name(mod, base) if mod else None
                    binfo = self.classes.get(key) if key else None
                    if binfo:
                        m = self.lookup_method(binfo, node.func.attr)
                        if m:
                            break
                add_site(node, m.qname if m else None, "call",
                         f"super().{node.func.attr}")
                return
            target = self._resolve_call(fn, dn, local_types)
            add_site(node, target, "call", dn or "<dynamic>")
            if last in COLLECTIVE_NAMES or \
                    (target is not None and target in BARRIER_QNAMES):
                fn.collectives.append((node.lineno, last))

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # nested defs are their own FunctionInfo
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        visit(item.context_expr)
                    lid = self._lock_identity(item.context_expr, fn)
                    if lid:
                        fn.acquires.append((lid, node.lineno, tuple(held)))
                        held.append(lid)
                        pushed += 1
                for stmt in node.body:
                    visit(stmt)
                for _ in range(pushed):
                    held.pop()
                return
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                ctor = _dotted(node.value.func)
                key = self.resolve_name(mod, ctor) if (ctor and mod) else None
                if key in self.classes:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_types[t.id] = key
            if isinstance(node, ast.Call):
                handle_call(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.node.body:
            visit(stmt)

    # -- cross-process stitching (rpc edges) -------------------------------

    @staticmethod
    def _param_names(fn_node: ast.AST) -> List[str]:
        a = fn_node.args
        return [p.arg for p in
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]

    def _send_method(self, node: ast.Call, ctx) -> Optional[Tuple[str, bool]]:
        """``(method, sync)`` when *node* is a protocol send carrying a
        ``pb.<METHOD>`` constant (same vocabulary as R18's extraction).
        ``sync`` is True only for the blocking request/reply primitive
        (final attribute literally ``call`` — ``RpcClient.call`` blocks on
        its reply); fire-and-forget / callback sends never wait."""
        from ray_tpu.devtools import dataflow as _df
        dotted = _dotted(node.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        is_send = (isinstance(node.func, ast.Attribute)
                   and node.func.attr in _df.SEND_ATTRS) or \
            bool(_df._SENDISH_RE.search(leaf))
        has_method_kw = any(kw.arg == "method" for kw in node.keywords)
        if not (is_send or has_method_kw):
            return None
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                m = _df._pb_method(sub, ctx)
                if m is not None:
                    return m, leaf == "call"
        return None

    def _dispatch_arms(self, fn: FunctionInfo
                       ) -> List[Tuple[str, ast.If]]:
        """``(method, If-node)`` per dispatch arm when *fn* is an
        RpcContext dispatcher.  Recognized only when the compared
        expression provably originates from a context-ish parameter
        (``ctx.method`` / ``env.method`` or a local assigned once from
        it) — a sender helper that merely branches on its own ``method``
        argument is NOT a dispatcher (under-approximation)."""
        from ray_tpu.devtools import dataflow as _df
        ctx_params = {p for p in self._param_names(fn.node)
                      if p in ("ctx", "env") or p.endswith("_ctx")}
        if not ctx_params:
            return []

        def from_ctx(e: ast.AST) -> bool:
            return (isinstance(e, ast.Attribute) and e.attr == "method"
                    and isinstance(e.value, ast.Name)
                    and e.value.id in ctx_params)

        meth_locals: Set[str] = set()
        for node in _df.FunctionDataflow._walk_pruned(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and from_ctx(node.value):
                meth_locals.add(node.targets[0].id)

        def is_method_expr(e: ast.AST) -> bool:
            return from_ctx(e) or (isinstance(e, ast.Name)
                                   and e.id in meth_locals)

        arms: List[Tuple[str, ast.If]] = []
        for node in _df.FunctionDataflow._walk_pruned(fn.node):
            if not isinstance(node, ast.If) or \
                    not isinstance(node.test, ast.Compare) or \
                    len(node.test.ops) != 1 or \
                    not is_method_expr(node.test.left):
                continue
            comp = node.test.comparators[0]
            if isinstance(node.test.ops[0], ast.Eq):
                m = _df._pb_method(comp, fn.ctx)
                if m is not None:
                    arms.append((m, node))
            elif isinstance(node.test.ops[0], ast.In) and \
                    isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                for elt in comp.elts:
                    m = _df._pb_method(elt, fn.ctx)
                    if m is not None:
                        arms.append((m, node))
        return arms

    def _file_stitch_facts(self, rel: str) -> dict:
        """JSON-able per-file stitch facts, a pure function of that one
        file's source (cacheable under its content hash): every protocol
        send site and every dispatcher function."""
        from ray_tpu.devtools import dataflow as _df
        sends: List[list] = []
        dispatchers: List[str] = []
        ctx = self.ctx_of[rel]
        for q, fn in self.functions.items():
            if fn.ctx is not ctx or fn.synthetic:
                continue
            for node in _df.FunctionDataflow._walk_pruned(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                ms = self._send_method(node, ctx)
                if ms is None:
                    continue
                site = fn.site_by_node.get(id(node))
                held = list(site.locks_held) if site is not None else []
                sends.append([q, node.lineno, ms[0], ms[1], held])
            if self._dispatch_arms(fn):
                dispatchers.append(q)
        return {"sends": sends, "dispatchers": sorted(dispatchers)}

    def _synthesize_arm(self, fn: FunctionInfo, method: str,
                        if_node: ast.If) -> Optional[str]:
        name = f"{fn.name}[{method}]"
        owner = f"{fn.cls}." if fn.cls else ""
        qname = f"{fn.module}:{owner}{name}"
        if qname in self.functions:
            return None              # duplicate arm for the same method
        node = ast.FunctionDef(name=name, args=fn.node.args,
                               body=list(if_node.body), decorator_list=[],
                               returns=None, type_comment=None)
        node.lineno = if_node.lineno
        node.col_offset = if_node.col_offset
        info = FunctionInfo(qname=qname, module=fn.module, cls=fn.cls,
                            name=name, node=node, ctx=fn.ctx,
                            synthetic="rpc-arm")
        self.functions[qname] = info
        self._analyze(info)
        self.rpc_arms.setdefault(method, []).append(qname)
        lo = min((s.lineno for s in if_node.body), default=if_node.lineno)
        hi = max((getattr(s, "end_lineno", s.lineno) for s in if_node.body),
                 default=if_node.lineno)
        self._arm_spans[qname] = (fn.qname, lo, hi)
        return qname

    def _stitch_rpc(self, cached: Dict[str, dict]) -> None:
        self._arm_spans: Dict[str, Tuple[str, int, int]] = {}
        for rel in sorted(self.ctx_of):
            facts = cached.get(rel)
            if facts is not None:
                self.stitch_hits += 1
            else:
                facts = self._file_stitch_facts(rel)
            self.stitch_facts[rel] = facts
        # pass 1: synthesize every dispatch arm (senders may live in
        # files sorted before their dispatcher)
        for rel in sorted(self.stitch_facts):
            for dq in self.stitch_facts[rel]["dispatchers"]:
                fn = self.functions.get(dq)
                if fn is None or fn.synthetic:
                    continue
                for method, if_node in self._dispatch_arms(fn):
                    self._synthesize_arm(fn, method, if_node)
        # pass 2: every send site becomes an rpc edge to each arm that
        # handles its method; a send written lexically inside an arm is
        # attributed to that arm too, so per-method closures see it
        for rel in sorted(self.stitch_facts):
            for q, line, method, sync, held in \
                    self.stitch_facts[rel]["sends"]:
                fn = self.functions.get(q)
                if fn is None:
                    continue
                targets = tuple(sorted(self.rpc_arms.get(method, ())))
                holders = [(q, fn)]
                for armq, (dispq, lo, hi) in self._arm_spans.items():
                    if dispq == q and lo <= line <= hi:
                        holders.append((armq, self.functions[armq]))
                for hq, hfn in holders:
                    for aq in targets:
                        hfn.call_sites.append(CallSite(
                            line=line, raw=f"rpc:{method}", target=aq,
                            kind="rpc", locks_held=tuple(held)))
                    self.rpc_sites.append(
                        (hq, line, method, bool(sync), tuple(held), targets))

    # -- field-level thread-safety plan (R23-R25) --------------------------

    def _file_field_facts(self, rel: str) -> dict:
        """JSON-able per-file field facts, a pure function of that one
        file's source (cacheable under its content hash): shared-attribute
        access records and atomicity splits per function (synthetic rpc
        arms included — their accesses carry the arm's thread context),
        guarded-by declarations, atomic-typed attributes, and the tracked
        module-global name set."""
        from ray_tpu.devtools import dataflow as _df
        ctx = self.ctx_of[rel]
        mod_name = module_name_for(rel)
        gnames = _df.module_global_names(ctx.tree)
        accesses: Dict[str, List[list]] = {}
        splits: Dict[str, List[list]] = {}
        for q in sorted(self.functions):
            fn = self.functions[q]
            if fn.ctx is not ctx:
                continue
            acc, spl = _df._FieldScan(fn, self, gnames).run()
            if acc:
                accesses[q] = acc
            if spl:
                splits[q] = spl
        return {
            "accesses": accesses,
            "splits": splits,
            "guarded": _df.guarded_decls(ctx, mod_name, self),
            "atomic": _df.atomic_attr_keys(ctx, mod_name, self),
            "globals": sorted(gnames),
        }

    def field_facts_all(self) -> Dict[str, dict]:
        if not self.field_facts:
            for rel in sorted(self.ctx_of):
                facts = self._field_cache.get(rel)
                if facts is not None:
                    self.field_hits += 1
                else:
                    facts = self._file_field_facts(rel)
                self.field_facts[rel] = facts
        return self.field_facts

    def field_plan(self) -> FieldPlan:
        """Join the per-file field facts with thread contexts and
        interprocedural must-hold locksets (memoized; built on demand by
        the first of R23-R25 to run).

        *Thread contexts* are the distinct roots code can run under:
        ``main`` (module import / direct API calls), every resolved
        ``spawn`` target (Thread/executor submit/call_soon_threadsafe),
        every ``Thread`` subclass ``run``, and every synthesized RPC
        dispatch arm.  Contexts propagate over ``call``/``loop`` edges
        (``loop`` resets the held-lock set: the task runs later); they do
        NOT cross ``spawn``/``rpc`` edges — the callee side is its own
        root.  Per (function, context) the must-held lockset is the
        intersection over all call paths from the root, so it can only
        shrink as more paths are discovered (sound for a race checker).
        """
        if self._plan is not None:
            return self._plan
        facts = self.field_facts_all()
        # 1. thread roots + spawn bookkeeping (for the happens-before-
        #    spawn suppression: a write before the spawn cannot race with
        #    the thread it starts)
        roots: Dict[str, Tuple[str, int, str]] = {}
        spawns_in: Dict[str, List[Tuple[str, int]]] = {}
        for q in sorted(self.functions):
            fn = self.functions[q]
            for s in fn.call_sites:
                if s.kind == "spawn" and s.target in self.functions:
                    roots.setdefault(
                        s.target, (fn.ctx.relpath, s.line,
                                   f"spawned from {q}"))
                    spawns_in.setdefault(q, []).append((s.target, s.line))
            if fn.synthetic == "rpc-arm":
                roots.setdefault(q, (fn.ctx.relpath, fn.node.lineno,
                                     "rpc dispatch arm"))
        for cq in sorted(self.classes):
            cls = self.classes[cq]
            if any(b.rsplit(".", 1)[-1] == "Thread" for b in cls.bases):
                run = cls.methods.get("run")
                if run is not None:
                    roots.setdefault(run.qname,
                                     (run.ctx.relpath, run.node.lineno,
                                      f"{cls.name}.run"))
        # 2. nested defs never become main entries: they only run when
        #    (and where) their enclosing function invokes them
        by_node = {id(f.node): q for q, f in self.functions.items()
                   if not f.synthetic}
        nested: Set[str] = set()
        for q, fn in self.functions.items():
            if fn.synthetic:
                continue
            for node in ast.walk(fn.node):
                if node is not fn.node and id(node) in by_node:
                    nested.add(by_node[id(node)])
        # 3. context fixpoint over call/loop edges
        callers: Dict[str, List[str]] = {}
        callees_of: Dict[str, List[CallSite]] = {}
        for q, fn in self.functions.items():
            outs = [s for s in fn.call_sites
                    if s.kind in ("call", "loop")
                    and s.target in self.functions]
            callees_of[q] = outs
            for s in outs:
                callers.setdefault(s.target, []).append(q)
        contexts: Dict[str, Dict[str, frozenset]] = {}
        work: List[str] = []
        for q in sorted(roots):
            contexts[q] = {q: frozenset()}
            work.append(q)
        for q in sorted(self.functions):
            fn = self.functions[q]
            if fn.synthetic or q in roots or q in nested or q in callers:
                continue
            contexts[q] = {"main": frozenset()}
            work.append(q)
        while work:
            q = work.pop()
            cur = contexts.get(q)
            if not cur:
                continue
            for s in callees_of.get(q, ()):
                tgt = contexts.setdefault(s.target, {})
                changed = False
                for cname, held in cur.items():
                    eff = frozenset() if s.kind == "loop" else \
                        held | frozenset(s.locks_held)
                    old = tgt.get(cname)
                    if old is None:
                        tgt[cname] = eff
                        changed = True
                    elif not (old <= eff):
                        tgt[cname] = old & eff
                        changed = True
                if changed:
                    work.append(s.target)
        # 4. construction-only closure: accesses there touch an instance
        #    no other thread can see yet (fresh-instance assumption —
        #    single-writer-before-spawn / immutable-after-init)
        init_names = {"__init__", "__new__", "__post_init__"}
        init_only: Set[str] = {q for q, fn in self.functions.items()
                               if fn.name in init_names and not fn.synthetic}
        changed = True
        while changed:
            changed = False
            for q, fn in self.functions.items():
                if q in init_only or fn.synthetic or q in roots:
                    continue
                cl = callers.get(q)
                if cl and all(c in init_only for c in cl):
                    init_only.add(q)
                    changed = True
        # 5. suppression sets + declarations, merged across files
        globals_of: Dict[str, Set[str]] = {}
        atomic_keys: Set[str] = set()
        guarded: Dict[str, Tuple[str, str, int]] = {}
        splits: List[Tuple[str, str, int, int, str]] = []
        for rel in sorted(facts):
            f = facts[rel]
            globals_of[module_name_for(rel)] = set(f.get("globals") or ())
            atomic_keys.update(f.get("atomic") or ())
            for key, lock, line in f.get("guarded") or ():
                guarded.setdefault(key, (lock, rel, line))
            for fnq in sorted(f.get("splits") or {}):
                for key, rline, wline, kind in f["splits"][fnq]:
                    splits.append((fnq, key, rline, wline, kind))
        # 6. expand access records with contexts; dedupe sites the stitch
        #    pass duplicated into rpc arms (same key/rel/line/mode) by
        #    unioning their context maps
        site_map: Dict[Tuple[str, str, int, str], FieldAccess] = {}
        for rel in sorted(facts):
            for fnq in sorted(facts[rel].get("accesses") or {}):
                fn = self.functions.get(fnq)
                if fn is None:
                    continue
                fctxs = contexts.get(fnq) or {}
                if not fctxs or fnq in init_only:
                    continue
                for line, key, mode, locks, wconst in \
                        facts[rel]["accesses"][fnq]:
                    if key in atomic_keys:
                        continue
                    if ":" not in key:
                        kmod, _, kname = key.rpartition(".")
                        tracked = globals_of.get(kmod)
                        if tracked is None or kname not in tracked:
                            continue
                    ident = (key, rel, line, mode)
                    fa = site_map.get(ident)
                    if fa is None:
                        fa = FieldAccess(key=key, rel=rel, line=line,
                                         mode=mode, fnq=fnq,
                                         locks=tuple(locks), wconst=wconst)
                        site_map[ident] = fa
                    for cname, held in fctxs.items():
                        eff = frozenset(locks) | held
                        old = fa.ctxs.get(cname)
                        fa.ctxs[cname] = eff if old is None else (old & eff)
        by_key: Dict[str, List[FieldAccess]] = {}
        for ident in sorted(site_map):
            fa = site_map[ident]
            by_key.setdefault(fa.key, []).append(fa)
        flag_keys = {
            key for key, lst in by_key.items()
            if any(a.mode == "write" for a in lst)
            and not any(a.mode == "mutate" for a in lst)
            and all(a.wconst == "flag" for a in lst if a.mode == "write")}
        self._plan = FieldPlan(
            roots=roots, contexts=contexts, accesses=by_key,
            guarded=guarded, splits=splits, init_only=init_only,
            atomic_keys=atomic_keys, flag_keys=flag_keys,
            spawns_in=spawns_in)
        return self._plan

    # -- fixpoint helpers for the interprocedural rules --------------------

    def _callees(self, fn: FunctionInfo,
                 kinds: Tuple[str, ...]) -> List[CallSite]:
        return [s for s in fn.call_sites
                if s.kind in kinds and s.target in self.functions]

    def transitive_paths(self, direct: Dict[str, List[Tuple[int, str]]],
                         kinds: Tuple[str, ...] = ("call",)
                         ) -> Dict[str, Dict[str, List[Tuple[str, int]]]]:
        """Fixpoint closure of a per-function fact set over the call graph.

        ``direct[qname]`` is a list of ``(line, key)`` facts established in
        that function.  Returns, per function, ``key -> witness path``
        where a path is ``[(qname, line), ...]`` ending at the function
        that establishes the fact directly.  The first-discovered witness
        is kept (deterministic: call sites are visited in source order).
        """
        out: Dict[str, Dict[str, List[Tuple[str, int]]]] = {}
        for q, facts in direct.items():
            d = out.setdefault(q, {})
            for line, key in facts:
                d.setdefault(key, [(q, line)])
        # reverse edges for the worklist
        callers: Dict[str, List[Tuple[str, CallSite]]] = {}
        for q, fn in self.functions.items():
            for site in self._callees(fn, kinds):
                callers.setdefault(site.target, []).append((q, site))
        work = list(out)
        while work:
            callee = work.pop()
            facts = out.get(callee, {})
            for caller, site in callers.get(callee, ()):
                d = out.setdefault(caller, {})
                changed = False
                for key, path in facts.items():
                    if key not in d:
                        d[key] = [(caller, site.line)] + path
                        changed = True
                if changed:
                    work.append(caller)
        return out
