"""Abstract sharding propagation for the SPMD lint rules (R27-R29).

The SPMD surface of the tree is *data*: ``ShardingRules`` tables map
logical axis names to mesh axes, ``AXIS_ORDER`` / ``Mesh(...)``
constructions declare the mesh-axis universe, and ``PartitionSpec`` /
``shard_map`` / ``pjit`` call sites consume both.  This module extracts
those facts per file (pure, JSON-able — they ride the incremental lint
cache keyed by content hash, exactly like the stitch and field facts of
:mod:`ray_tpu.devtools.callgraph`) and joins them into a whole-tree
:class:`ShardModel` the R27/R28/R29 project rules query.

The propagation lattice is deliberately tiny: every value is either a
*known constant* (a string, ``None``, or a tuple of strings, resolved
through single-assignment locals in the enclosing scope chain) or ``"?"``
(top).  Anything dynamic — a spec built from parameters, a mesh with
computed axis names, a rules table spread from ``**kwargs`` — degrades to
top, and top never produces a finding.  When a file constructs a mesh or
a rules table we cannot enumerate, the whole corresponding universe is
marked *open* and membership checks shut off tree-wide: the rules
under-report but never invent, the same stance as R10-R26.

:func:`build_manifest` turns the same model into ``comms_manifest.json``
— the static plan of every explicit ``ray_tpu.collective`` op (keyed by
group name) and every ``jax.lax`` collective with a resolved mesh axis
(keyed ``axis:<name>``), each with its busbw wire-factor formula.  The
formulas mirror ``observability/comms.py``'s ``_BUSBW`` table (the
EQuARX byte counts); ``ray_tpu.doctor --comms-baseline`` cross-checks
the runtime ledger against this plan and reports unplanned collectives
as drift.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["file_shard_facts", "ShardModel", "build_manifest",
           "wire_factor", "WIRE_FORMULAS", "format_spec", "UNKNOWN"]

UNKNOWN = "?"

# jax.lax collective primitives that move bytes over a named mesh axis
# (axis name is the second positional argument / ``axis_name`` kwarg).
LAX_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute",
    "all_gather", "all_to_all", "psum_scatter",
})

# Public ops of ray_tpu/collective/collective.py, by ledger op name.
EXPLICIT_OPS = frozenset({
    "allreduce", "reduce", "broadcast", "allgather", "reducescatter",
    "send", "recv", "barrier",
})

# Human-readable busbw wire-factor formulas per op, mirroring
# observability/comms.py _BUSBW (asserted equal by the devtools tests).
WIRE_FORMULAS: Dict[str, str] = {
    "allreduce": "2*(n-1)/n", "psum": "2*(n-1)/n", "pmean": "2*(n-1)/n",
    "pmax": "2*(n-1)/n", "pmin": "2*(n-1)/n",
    "allgather": "(n-1)/n", "all_gather": "(n-1)/n",
    "reducescatter": "(n-1)/n", "psum_scatter": "(n-1)/n",
    "all_to_all": "(n-1)/n",
}


def wire_factor(op: str, n: int) -> float:
    """Numeric busbw factor for *op* over an *n*-member group — the same
    ring formulas ``comms._BUSBW`` applies to the runtime ledger."""
    if op in ("allreduce", "psum", "pmean", "pmax", "pmin"):
        return 2.0 * (n - 1) / n if n else 1.0
    if op in ("allgather", "all_gather", "reducescatter", "psum_scatter",
              "all_to_all"):
        return (n - 1) / n if n else 1.0
    return 1.0


def format_spec(parts: Sequence[Any]) -> str:
    """Render abstract spec parts back as PartitionSpec source text."""
    def one(p: Any) -> str:
        if p is None:
            return "None"
        if isinstance(p, list):
            return "(" + ", ".join(repr(x) for x in p) + ")"
        if p == UNKNOWN:
            return "?"
        return repr(p)
    return "P(" + ", ".join(one(p) for p in parts) + ")"


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return base + "." + node.attr if base else None
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_jax_name(dn: Optional[str], origin: Dict[str, str],
                 leaf: str) -> bool:
    """True when dotted name *dn* plausibly resolves into jax: either the
    text starts with ``jax.`` or the head's import origin mentions jax
    (the ``_private.jax_compat`` shim counts, as in R21)."""
    if not dn:
        return False
    if dn.split(".")[-1] != leaf:
        return False
    if dn.startswith("jax."):
        return True
    head = dn.split(".")[0]
    return "jax" in origin.get(head, "")


def _const_str_tuple(node: ast.AST) -> Optional[List[str]]:
    """A tuple/list literal of string constants, or a single string."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def _strip_trailing_none(parts: List[Any]) -> List[Any]:
    out = list(parts)
    while out and out[-1] is None:
        out.pop()
    return out


def _specs_equal(a: List[Any], b: List[Any]) -> bool:
    """Equality of two fully-known spec part lists, modulo the trailing
    ``None`` padding PartitionSpec itself ignores."""
    return _strip_trailing_none(a) == _strip_trailing_none(b)


def _fully_known(parts: Sequence[Any]) -> bool:
    return all(p != UNKNOWN for p in parts)


class _Scope:
    """One lexical scope's constant environment, chained to its parent."""

    def __init__(self, parent: Optional["_Scope"] = None):
        self.parent = parent
        self.consts: Dict[str, Any] = {}      # name -> str constant
        self.specs: Dict[str, Any] = {}       # name -> spec parts | UNKNOWN
        self.shardings: Dict[str, Any] = {}   # name -> spec parts (NamedSharding)
        self.producers: Dict[str, Tuple[int, List[Any]]] = {}  # var -> (line, parts)
        self.consumers: Dict[str, Tuple[str, List[Any]]] = {}  # var -> (kind, in_specs)
        self.defs: Dict[str, List[Tuple[int, int]]] = {}       # name -> [(min, max)]

    def _lookup(self, attr: str, name: str) -> Any:
        s: Optional[_Scope] = self
        while s is not None:
            d = getattr(s, attr)
            if name in d:
                return d[name]
            s = s.parent
        return None

    def const(self, name: str) -> Any:
        return self._lookup("consts", name)

    def spec_of(self, name: str) -> Any:
        return self._lookup("specs", name)

    def sharding_of(self, name: str) -> Any:
        return self._lookup("shardings", name)

    def consumer_of(self, name: str) -> Any:
        return self._lookup("consumers", name)

    def arities_of(self, name: str) -> Any:
        return self._lookup("defs", name)


class _FileScanner:
    """Single-pass fact extraction for one parsed file."""

    def __init__(self, ctx: Any):
        self.ctx = ctx
        self.origin: Dict[str, str] = getattr(ctx, "import_origin", {})
        self.facts: Dict[str, Any] = {
            "rules": {},            # table name -> sorted logical keys
            "override_names": [],   # kwarg names seen at with_overrides()
            "axis_order": [],       # tuples assigned to *AXIS_ORDER* names
            "mesh_ctors": [],       # axis names from Mesh(...) literals
            "dynamic_mesh": False,  # a mesh with unresolvable axis names
            "dynamic_rules": False,  # a rules table we cannot enumerate
            "axis_sites": [],       # [line, axis, kind]
            "dup_sites": [],        # [line, axis]
            "arity_sites": [],      # [line, got, want_lo, want_hi, callee]
            "logical_sites": [],    # [line, name, src]
            "reshard_sites": [],    # [line, argpos, got, want, callee]
            "donate_sites": [],     # [line, argpos, got, want]
            "collective_sites": [],  # [line, op, group]
            "lax_sites": [],        # [line, op, axis]
        }
        self._seen_p: set = set()   # id() of P-call nodes already recorded

    # -- entry ------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        tree = self.ctx.tree
        self._module_pass(tree)
        root = _Scope()
        self._scan_block(tree.body, root, in_logical_fn=False)
        f = self.facts
        f["override_names"] = sorted(set(f["override_names"]))
        f["mesh_ctors"] = sorted(set(f["mesh_ctors"]))
        return f

    # -- module-level tables ----------------------------------------------

    def _module_pass(self, tree: ast.Module) -> None:
        for node in tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            name = tgt.id
            if name.endswith("RULES") and isinstance(node.value, ast.Dict):
                table = self._rules_table(node.value)
                if table is None:
                    self.facts["dynamic_rules"] = True
                else:
                    self.facts["rules"][name] = sorted(table)
            if "AXIS_ORDER" in name:
                axes = _const_str_tuple(node.value)
                if axes:
                    self.facts["axis_order"].append(axes)

    def _rules_table(self, node: ast.Dict) -> Optional[List[str]]:
        """Logical keys of a rules-table dict literal; None if dynamic.
        Mesh-axis *values* are recorded as checkable axis sites."""
        keys: List[str] = []
        for k, v in zip(node.keys, node.values):
            if k is None:  # **spread
                return None
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                return None
            keys.append(k.value)
            axes = _const_str_tuple(v)
            if axes:
                for ax in axes:
                    self.facts["axis_sites"].append(
                        [v.lineno, ax, "rules-table"])
        return keys

    # -- scope machinery ---------------------------------------------------

    def _scan_block(self, stmts: List[ast.stmt], scope: _Scope,
                    in_logical_fn: bool) -> None:
        self._prepass(stmts, scope)
        for stmt in stmts:
            self._visit_stmt(stmt, scope, in_logical_fn)

    def _prepass(self, stmts: List[ast.stmt], scope: _Scope) -> None:
        """Collect single-assignment locals usable as constants: strings,
        P(...) specs, NamedSharding specs, shard_map/jit consumers,
        device_put producers, and def/lambda arities."""
        counts: Dict[str, int] = {}
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.defs.setdefault(stmt.name, []).append(
                    _arity_range(stmt.args))
                continue
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            tgt = stmt.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            name, val = tgt.id, stmt.value
            counts[name] = counts.get(name, 0) + 1
            if counts[name] > 1:
                # reassigned: drop every interpretation except lambdas,
                # which accumulate (branch-dependent bodies are all real)
                scope.consts.pop(name, None)
                scope.specs.pop(name, None)
                scope.shardings.pop(name, None)
                scope.producers.pop(name, None)
                scope.consumers.pop(name, None)
            if isinstance(val, ast.Constant) and isinstance(val.value, str):
                if counts[name] == 1:
                    scope.consts[name] = val.value
            elif isinstance(val, ast.Lambda):
                scope.defs.setdefault(name, []).append(_arity_range(val.args))
            elif isinstance(val, ast.Call):
                if counts[name] > 1:
                    continue
                parts = self._p_parts(val)
                if parts is not None:
                    scope.specs[name] = parts
                    continue
                parts = self._namedsharding_parts(val, scope)
                if parts is not None:
                    scope.shardings[name] = parts
                    continue
                prod = self._producer_parts(val, scope)
                if prod is not None:
                    scope.producers[name] = (stmt.lineno, prod)
                    continue
                cons = self._consumer_specs(val, scope)
                if cons is not None:
                    scope.consumers[name] = cons

    def _visit_stmt(self, stmt: ast.stmt, scope: _Scope,
                    in_logical_fn: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in stmt.decorator_list:
                self._visit_expr(dec, scope, in_logical_fn)
            child = _Scope(scope)
            logical = in_logical_fn or "logical_axes" in stmt.name
            self._scan_block(stmt.body, child, logical)
            return
        if isinstance(stmt, ast.ClassDef):
            for dec in stmt.decorator_list:
                self._visit_expr(dec, scope, in_logical_fn)
            # class body shares the enclosing constant env read-only
            self._scan_block(stmt.body, _Scope(scope), in_logical_fn)
            return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._visit_expr(node, scope, in_logical_fn)
            elif isinstance(node, ast.stmt):
                self._visit_stmt(node, scope, in_logical_fn)
            elif isinstance(node, (ast.excepthandler, ast.withitem,
                                   ast.match_case)):
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, ast.expr):
                        self._visit_expr(sub, scope, in_logical_fn)
                    elif isinstance(sub, ast.stmt):
                        self._visit_stmt(sub, scope, in_logical_fn)

    def _visit_expr(self, node: ast.AST, scope: _Scope,
                    in_logical_fn: bool) -> None:
        if isinstance(node, ast.Lambda):
            child = _Scope(scope)
            self._visit_expr(node.body, child, in_logical_fn)
            return
        if isinstance(node, ast.Call):
            self._handle_call(node, scope)
        if in_logical_fn and isinstance(node, (ast.Tuple, ast.List)):
            names = self._logical_tuple(node)
            if names is not None:
                for nm in names:
                    self.facts["logical_sites"].append(
                        [node.lineno, nm, "logical-axes"])
                return  # elements consumed; nothing nested to visit
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, scope, in_logical_fn)
            elif isinstance(child, (ast.comprehension, ast.keyword,
                                    ast.Starred)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        self._visit_expr(sub, scope, in_logical_fn)

    # -- detectors ---------------------------------------------------------

    def _handle_call(self, node: ast.Call, scope: _Scope) -> None:
        dn = _dotted(node.func)
        leaf = dn.split(".")[-1] if dn else ""
        if leaf in ("PartitionSpec", "P") and self._is_p_call(node, dn):
            self._record_p(node, scope)
        elif leaf == "Mesh" and _is_jax_name(dn, self.origin, "Mesh"):
            self._record_mesh(node)
        elif leaf == "shard_map" and _is_jax_name(dn, self.origin,
                                                  "shard_map"):
            self._record_shard_map(node, scope)
        elif leaf in ("jit", "pjit") and _is_jax_name(dn, self.origin, leaf):
            self._record_jit(node, node, scope)
        elif leaf == "partial" and node.args:
            inner = _dotted(node.args[0])
            ileaf = inner.split(".")[-1] if inner else ""
            if ileaf in ("jit", "pjit") and _is_jax_name(
                    inner, self.origin, ileaf):
                self._record_jit(node, node, scope)
        elif leaf in LAX_COLLECTIVES and self._is_lax_collective(dn):
            self._record_lax(node, leaf, scope)
        elif leaf in EXPLICIT_OPS and self._is_explicit_op(dn, leaf):
            self._record_explicit(node, leaf)
        elif leaf == "with_overrides":
            self._record_overrides(node)
        elif leaf in ("spec", "sharding") and self._is_rules_recv(node):
            self._record_logical_call(node, leaf)
        elif leaf == "shard_pytree":
            self._record_axes_tree(node)
        elif leaf == "ShardingRules":
            self._record_rules_ctor(node)
        # R28: call through a known shard_map/jit consumer
        if isinstance(node.func, ast.Name):
            cons = scope.consumer_of(node.func.id)
            if cons is not None:
                self._check_reshard(node, node.func.id, cons, scope)

    # P / PartitionSpec ----------------------------------------------------

    def _is_p_call(self, node: ast.Call, dn: Optional[str]) -> bool:
        if not dn:
            return False
        head = dn.split(".")[0]
        org = self.origin.get(head, "")
        return ("PartitionSpec" in org or "jax" in org
                or dn.startswith("jax."))

    def _p_parts(self, node: ast.AST) -> Optional[List[Any]]:
        """Abstract parts of a P(...)/PartitionSpec(...) call, else None."""
        if not isinstance(node, ast.Call):
            return None
        dn = _dotted(node.func)
        leaf = dn.split(".")[-1] if dn else ""
        if leaf not in ("PartitionSpec", "P") or not self._is_p_call(node, dn):
            return None
        parts: List[Any] = []
        for arg in node.args:
            if isinstance(arg, ast.Starred):
                parts.append(UNKNOWN)
                continue
            parts.append(self._part_value(arg, None))
        return parts

    def _part_value(self, node: ast.AST, scope: Optional[_Scope]) -> Any:
        if isinstance(node, ast.Constant):
            if node.value is None:
                return None
            if isinstance(node.value, str):
                return node.value
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            vals = _const_str_tuple(node)
            return list(vals) if vals is not None else UNKNOWN
        if isinstance(node, ast.Name) and scope is not None:
            c = scope.const(node.id)
            if c is not None:
                return c
        return UNKNOWN

    def _record_p(self, node: ast.Call, scope: _Scope) -> None:
        if id(node) in self._seen_p:
            return
        self._seen_p.add(id(node))
        parts: List[Any] = []
        for arg in node.args:
            if isinstance(arg, ast.Starred):
                parts.append(UNKNOWN)
            else:
                parts.append(self._part_value(arg, scope))
        used: List[str] = []
        for p in parts:
            axes = p if isinstance(p, list) else ([p] if isinstance(p, str)
                                                  else [])
            for ax in axes:
                if ax == UNKNOWN:
                    continue
                self.facts["axis_sites"].append([node.lineno, ax, "spec"])
                if ax in used:
                    self.facts["dup_sites"].append([node.lineno, ax])
                used.append(ax)

    # Mesh -----------------------------------------------------------------

    def _record_mesh(self, node: ast.Call) -> None:
        axes_node: Optional[ast.AST] = None
        if len(node.args) >= 2:
            axes_node = node.args[1]
        for kw in node.keywords:
            if kw.arg == "axis_names":
                axes_node = kw.value
        if axes_node is None:
            self.facts["dynamic_mesh"] = True
            return
        axes = _const_str_tuple(axes_node)
        if axes is None and isinstance(axes_node, ast.Name) and \
                "AXIS_ORDER" in axes_node.id and self.facts["axis_order"]:
            # e.g. Mesh(arr, AXIS_ORDER): resolve via the module table
            axes = self.facts["axis_order"][0]
        if axes is None:
            self.facts["dynamic_mesh"] = True
        else:
            self.facts["mesh_ctors"].extend(axes)

    # shard_map ------------------------------------------------------------

    def _in_specs_list(self, node: ast.AST,
                       scope: _Scope) -> Optional[List[Any]]:
        """Resolve an in_specs/out_specs expression to a list of abstract
        specs (each a parts list or UNKNOWN); None when the shape itself
        is unresolvable (so even the arity is unknown)."""
        if isinstance(node, (ast.Tuple, ast.List)):
            out: List[Any] = []
            for elt in node.elts:
                if isinstance(elt, ast.Starred):
                    return None
                out.append(self._one_spec(elt, scope))
            return out
        one = self._one_spec(node, scope)
        return [one] if one is not UNKNOWN else None

    def _one_spec(self, node: ast.AST, scope: _Scope) -> Any:
        parts = self._p_parts(node)
        if parts is not None:
            resolved = []
            for i, arg in enumerate(node.args):  # type: ignore[union-attr]
                if isinstance(arg, ast.Starred):
                    resolved.append(UNKNOWN)
                else:
                    resolved.append(self._part_value(arg, scope))
            return resolved
        if isinstance(node, ast.Name):
            sp = scope.spec_of(node.id)
            if sp is not None:
                return sp
        return UNKNOWN

    def _record_shard_map(self, node: ast.Call, scope: _Scope) -> None:
        in_specs = None
        for kw in node.keywords:
            if kw.arg == "in_specs":
                in_specs = self._in_specs_list(kw.value, scope)
        if in_specs is None or not node.args:
            return
        callee = node.args[0]
        callee_name = _dotted(callee) or "<fn>"
        arities: List[Tuple[int, int]] = []
        if isinstance(callee, ast.Lambda):
            arities = [_arity_range(callee.args)]
            callee_name = "<lambda>"
        elif isinstance(callee, ast.Name):
            found = scope.arities_of(callee.id)
            if found:
                arities = list(found)
        got = len(in_specs)
        if arities and not any(lo <= got <= hi for lo, hi in arities):
            lo, hi = arities[0]
            want = str(lo) if lo == hi else f"{lo}..{hi}"
            self.facts["arity_sites"].append(
                [node.lineno, got, want, callee_name])

    def _consumer_specs(self, node: ast.Call,
                        scope: _Scope) -> Optional[Tuple[str, List[Any]]]:
        """in_specs/in_shardings of a shard_map or jit call assigned to a
        local — the consumer side of the R28 boundary check."""
        dn = _dotted(node.func)
        leaf = dn.split(".")[-1] if dn else ""
        if leaf == "shard_map" and _is_jax_name(dn, self.origin,
                                                "shard_map"):
            for kw in node.keywords:
                if kw.arg == "in_specs":
                    specs = self._in_specs_list(kw.value, scope)
                    if specs is not None:
                        return ("shard_map", specs)
        if leaf in ("jit", "pjit") and _is_jax_name(dn, self.origin, leaf):
            for kw in node.keywords:
                if kw.arg == "in_shardings":
                    specs = self._in_specs_list(kw.value, scope)
                    if specs is not None:
                        return (leaf, specs)
        return None

    # producers (R28) -------------------------------------------------------

    def _namedsharding_parts(self, node: ast.Call,
                             scope: _Scope) -> Optional[List[Any]]:
        dn = _dotted(node.func)
        if not dn or dn.split(".")[-1] != "NamedSharding":
            return None
        if not _is_jax_name(dn, self.origin, "NamedSharding"):
            head = dn.split(".")[0]
            if "NamedSharding" not in self.origin.get(head, ""):
                return None
        spec_node: Optional[ast.AST] = node.args[1] if len(node.args) >= 2 \
            else None
        for kw in node.keywords:
            if kw.arg == "spec":
                spec_node = kw.value
        if spec_node is None:
            return None
        parts = self._one_spec(spec_node, scope)
        return parts if isinstance(parts, list) else None

    def _producer_parts(self, node: ast.Call,
                        scope: _Scope) -> Optional[List[Any]]:
        """``x = jax.device_put(v, <sharding>)`` (or
        make_array_from_single_device_arrays): the producer side."""
        dn = _dotted(node.func)
        leaf = dn.split(".")[-1] if dn else ""
        if leaf == "device_put" and _is_jax_name(dn, self.origin,
                                                 "device_put"):
            sh = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "device":
                    sh = kw.value
        elif leaf == "make_array_from_single_device_arrays" and \
                _is_jax_name(dn, self.origin, leaf):
            sh = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "sharding":
                    sh = kw.value
        else:
            return None
        if sh is None:
            return None
        if isinstance(sh, ast.Call):
            parts = self._namedsharding_parts(sh, scope)
            if parts is not None:
                return parts
        if isinstance(sh, ast.Name):
            parts = scope.sharding_of(sh.id)
            if parts is not None:
                return parts
        return None

    def _check_reshard(self, node: ast.Call, fname: str,
                       cons: Tuple[str, List[Any]], scope: _Scope) -> None:
        kind, in_specs = cons
        for i, arg in enumerate(node.args):
            if i >= len(in_specs):
                break
            if not isinstance(arg, ast.Name):
                continue
            prod = scope._lookup("producers", arg.id)
            if prod is None:
                continue
            _line, got = prod
            want = in_specs[i]
            if not isinstance(want, list) or not isinstance(got, list):
                continue
            if not (_fully_known(got) and _fully_known(want)):
                continue
            if not _specs_equal(got, want):
                self.facts["reshard_sites"].append(
                    [node.lineno, i, format_spec(got), format_spec(want),
                     fname])

    # jit donation (R28) ----------------------------------------------------

    def _record_jit(self, node: ast.Call, kw_holder: ast.Call,
                    scope: _Scope) -> None:
        donate: Optional[List[int]] = None
        in_sh = out_sh = None
        for kw in kw_holder.keywords:
            if kw.arg == "donate_argnums":
                donate = _int_positions(kw.value)
            elif kw.arg == "in_shardings":
                in_sh = self._in_specs_list(kw.value, scope)
            elif kw.arg == "out_shardings":
                out_sh = self._in_specs_list(kw.value, scope)
        if not donate or in_sh is None or out_sh is None:
            return
        for pos in donate:
            if pos >= len(in_sh):
                continue
            got = in_sh[pos]
            want = out_sh[pos] if len(out_sh) > 1 else out_sh[0]
            if not isinstance(got, list) or not isinstance(want, list):
                continue
            if not (_fully_known(got) and _fully_known(want)):
                continue
            if not _specs_equal(got, want):
                self.facts["donate_sites"].append(
                    [node.lineno, pos, format_spec(got), format_spec(want)])

    # collectives (R29) -----------------------------------------------------

    def _is_lax_collective(self, dn: Optional[str]) -> bool:
        if not dn:
            return False
        if ".lax." in dn or dn.startswith("lax."):
            head = dn.split(".")[0]
            return dn.startswith("jax.") or "jax" in self.origin.get(head, "")
        head = dn.split(".")[0]
        return "jax" in self.origin.get(head, "") and "lax" in \
            self.origin.get(head, "")

    def _record_lax(self, node: ast.Call, op: str, scope: _Scope) -> None:
        axis_node: Optional[ast.AST] = node.args[1] if len(node.args) >= 2 \
            else None
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                axis_node = kw.value
        axis: Any = UNKNOWN
        if axis_node is not None:
            axis = self._part_value(axis_node, scope)
            if isinstance(axis, list):  # multi-axis collective: any str ok
                axis = axis[0] if len(axis) == 1 else UNKNOWN
            if axis is None:
                axis = UNKNOWN
        self.facts["lax_sites"].append([node.lineno, op, axis])

    def _is_explicit_op(self, dn: Optional[str], leaf: str) -> bool:
        if not dn:
            return False
        head = dn.split(".")[0]
        org = self.origin.get(head, "")
        full = (org + dn[len(head):]) if org else dn
        return "collective" in full and (
            full.startswith("ray_tpu.") or org.startswith("ray_tpu"))

    def _record_explicit(self, node: ast.Call, op: str) -> None:
        group: Any = None
        dynamic = False
        for kw in node.keywords:
            if kw.arg == "group_name":
                if isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, str):
                    group = kw.value.value
                else:
                    dynamic = True
            elif kw.arg is None:
                dynamic = True  # **kwargs may carry group_name
        if group is None:
            for arg in node.args[1:]:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    group = arg.value
                    break
                if not isinstance(arg, ast.Constant):
                    dynamic = True
        if group is None:
            group = "*" if dynamic else "default"
        self.facts["collective_sites"].append([node.lineno, op, group])

    # logical-axis uses (R27d) ----------------------------------------------

    def _record_overrides(self, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg is None:
                self.facts["dynamic_rules"] = True
                continue
            self.facts["override_names"].append(kw.arg)
            axes = _const_str_tuple(kw.value)
            if axes:
                for ax in axes:
                    self.facts["axis_sites"].append(
                        [kw.value.lineno, ax, "override"])

    def _is_rules_recv(self, node: ast.Call) -> bool:
        if not isinstance(node.func, ast.Attribute):
            return False
        recv = _dotted(node.func.value)
        return bool(recv) and "rules" in recv.split(".")[-1].lower()

    def _record_logical_call(self, node: ast.Call, leaf: str) -> None:
        # rules.spec(axes) / rules.sharding(mesh, axes)
        idx = 0 if leaf == "spec" else 1
        arg = node.args[idx] if len(node.args) > idx else None
        for kw in node.keywords:
            if kw.arg == "logical_axes":
                arg = kw.value
        if arg is None or not isinstance(arg, (ast.Tuple, ast.List)):
            return
        names = self._logical_tuple(arg)
        if names is None:
            return
        for nm in names:
            self.facts["logical_sites"].append([arg.lineno, nm, "spec-call"])

    def _logical_tuple(self, node: ast.AST) -> Optional[List[str]]:
        """Tuple/list literal of logical names: str and None elements only,
        at least one str."""
        if not isinstance(node, (ast.Tuple, ast.List)) or not node.elts:
            return None
        out: List[str] = []
        for elt in node.elts:
            if not isinstance(elt, ast.Constant):
                return None
            if isinstance(elt.value, str):
                out.append(elt.value)
            elif elt.value is not None:
                return None
        return out if out else None

    def _record_axes_tree(self, node: ast.Call) -> None:
        axes = node.args[1] if len(node.args) >= 2 else None
        for kw in node.keywords:
            if kw.arg == "axes_tree":
                axes = kw.value
        if not isinstance(axes, ast.Dict):
            return
        for v in axes.values:
            names = self._logical_tuple(v)
            if names:
                for nm in names:
                    self.facts["logical_sites"].append(
                        [v.lineno, nm, "axes-tree"])

    def _record_rules_ctor(self, node: ast.Call) -> None:
        for arg in node.args:
            if isinstance(arg, ast.Dict):
                table = self._rules_table(arg)
                if table is None:
                    self.facts["dynamic_rules"] = True
                else:
                    self.facts["rules"].setdefault(
                        f"<ctor:{node.lineno}>", sorted(table))
            else:
                self.facts["dynamic_rules"] = True
        for kw in node.keywords:
            if kw.arg == "rules" and isinstance(kw.value, ast.Dict):
                table = self._rules_table(kw.value)
                if table is None:
                    self.facts["dynamic_rules"] = True
                else:
                    self.facts["rules"].setdefault(
                        f"<ctor:{node.lineno}>", sorted(table))
            elif kw.arg is not None and not isinstance(kw.value, ast.Dict):
                # other dataclass fields (none today) — stay conservative
                self.facts["dynamic_rules"] = True


def _arity_range(args: ast.arguments) -> Tuple[int, int]:
    n = len(args.posonlyargs) + len(args.args)
    lo = n - len(args.defaults)
    hi = 10 ** 6 if args.vararg is not None else n
    return (lo, hi)


def _int_positions(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def file_shard_facts(ctx: Any) -> Dict[str, Any]:
    """Pure, JSON-able SPMD facts for one file — the cacheable unit."""
    return _FileScanner(ctx).run()


class ShardModel:
    """Whole-tree join of per-file shard facts.

    ``cached`` maps relpath -> previously computed facts (content-hash
    validated by the caller); files present there skip re-extraction and
    count toward ``hits`` for the ``raylint-cache: ... shard S/T`` line.
    """

    def __init__(self, ctxs: Sequence[Any],
                 cached: Optional[Dict[str, dict]] = None):
        cached = cached or {}
        self.facts: Dict[str, Dict[str, Any]] = {}
        self.hits = 0
        for ctx in ctxs:
            f = cached.get(ctx.relpath)
            if f is not None:
                self.hits += 1
            else:
                f = file_shard_facts(ctx)
            self.facts[ctx.relpath] = f
        self.mesh_axes: set = set()
        self.logical_names: set = set()
        self._open_mesh = False
        self._open_rules = False
        for f in self.facts.values():
            for order in f["axis_order"]:
                self.mesh_axes.update(order)
            self.mesh_axes.update(f["mesh_ctors"])
            self._open_mesh = self._open_mesh or f["dynamic_mesh"]
            for keys in f["rules"].values():
                self.logical_names.update(keys)
            self.logical_names.update(f["override_names"])
            self._open_rules = self._open_rules or f["dynamic_rules"]

    def mesh_closed(self) -> bool:
        """True when the mesh-axis universe is known exactly — only then
        may axis-membership checks fire (under-approximation stance)."""
        return bool(self.mesh_axes) and not self._open_mesh

    def rules_closed(self) -> bool:
        return bool(self.logical_names) and not self._open_rules


def build_manifest(model: ShardModel) -> Dict[str, Any]:
    """The static collective-cost plan: every resolvable collective site,
    keyed by runtime ledger group (explicit ops) or ``axis:<mesh-axis>``
    (shard_map/pjit-implied jax.lax collectives), with its busbw
    wire-factor formula.  ``unresolved_sites`` counts the sites whose
    axis or group degraded to top — the plan never claims to cover them."""
    groups: Dict[str, Dict[str, Any]] = {}
    unresolved = 0

    def ent(group: str, op: str) -> Dict[str, Any]:
        return groups.setdefault(group, {}).setdefault(
            op, {"sites": [], "wire_formula": WIRE_FORMULAS.get(op, "1")})

    for rel in sorted(model.facts):
        f = model.facts[rel]
        for line, op, group in f["collective_sites"]:
            ent(group, op)["sites"].append([rel, int(line)])
        for line, op, axis in f["lax_sites"]:
            if axis == UNKNOWN:
                unresolved += 1
                continue
            if model.mesh_closed() and axis not in model.mesh_axes:
                continue  # an R29 finding, not a plan entry
            ent("axis:" + axis, op)["sites"].append([rel, int(line)])
    return {"version": 1, "tool": "raylint/R29",
            "mesh_axes": sorted(model.mesh_axes),
            "unresolved_sites": unresolved, "groups": groups}
