"""Framework-aware AST lint engine.

The engine walks every ``*.py`` file under the given roots, parses each
once, and runs two kinds of rules over the parse trees:

- **file rules** see one file at a time (R1, R3, R4, R5);
- **project rules** see the whole tree at once and can correlate across
  files (R2 lock-order consistency, R6 proto/pb2 drift).

Suppression has two layers, mirroring the sanitizer stance of the native
side (``run_sanitizers.sh``):

- an inline justification comment on the finding line or the line above::

      except Exception:  # raylint: allow(swallow) best-effort close
          pass

  The tag in ``allow(...)`` is the rule's short tag (``swallow``,
  ``lock-order``, ...) or its id (``R4``); ``allow(all)`` suppresses every
  rule on that line.  The justification text after the tag is *required
  culture*, not enforced syntax.
- a per-file allowlist baseline (``--baseline FILE``): lines of
  ``RULE<whitespace>path`` that tolerate pre-existing findings while a
  cleanup is in flight.  The shipped baseline is empty — the tree lints
  clean — and CI fails on any finding not covered by one of the two.

Rules (see ARCHITECTURE.md "Static analysis & concurrency invariants"):

==== ============== ====================================================
id   tag            what it catches
==== ============== ====================================================
R1   async-blocking blocking call (``time.sleep``, ``.result()``,
                    lock ``.acquire()`` without timeout, ``ray_tpu.get``)
                    inside an ``async def`` body
R2   lock-order     two locks statically acquired in both A→B and B→A
                    nesting orders anywhere in the tree
R3   unguarded-state self-attribute written both from a thread-entry
                    method and from on-thread code with no lock held
R4   swallow        ``except Exception:`` that neither re-raises, logs,
                    nor uses the caught exception
R5   host-sync      host-device sync (``.item()``, ``float()``,
                    ``np.asarray``, ``jax.device_get``) reachable from a
                    jitted step function
R6   proto-drift    field/enum-number drift between ``raytpu.proto`` and
                    the committed ``raytpu_pb2.py``
R7   bare-retry     hand-rolled retry loop: constant ``time.sleep`` inside
                    a loop that also catches exceptions (use
                    ``ray_tpu._private.backoff.BackoffPolicy``)
R8   hidden-copy    ``bytes(<memoryview/bytearray/slice>)`` casts and
                    ``b"".join`` chunk reassembly inside modules marked
                    ``# raylint: hot-path`` (payload-plane copies the
                    zero-copy data plane exists to eliminate)
R9   direct-checkpoint-io
                    ``.to_directory()`` / ``.from_directory()`` calls in
                    the ``train/``, ``tune/`` or ``serve/`` subtrees —
                    directory blobs bypass the checkpoint engine's
                    crash-atomic manifest commit; go through
                    ``ray_tpu.checkpoint`` (the engine itself and
                    ``air/`` are out of scope)
==== ============== ====================================================
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set, Tuple

__all__ = ["Finding", "LintEngine", "rule", "project_rule", "RULES",
           "PROJECT_RULES"]

_ALLOW_RE = re.compile(r"#\s*raylint:\s*allow\(([A-Za-z0-9_,\- ]+)\)")


@dataclass(frozen=True)
class Finding:
    rule: str          # "R4"
    tag: str           # "swallow"
    path: str          # path relative to the lint root
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}({self.tag}): {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "tag": self.tag, "path": self.path,
                "line": self.line, "message": self.message}


class FileContext:
    """One parsed source file plus the lookups rules keep re-needing."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.allow = self._collect_allows(source)
        # name -> module it was imported from ("from ray_tpu import get")
        self.from_imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = node.module

    @staticmethod
    def _collect_allows(source: str) -> Dict[int, Set[str]]:
        """line -> set of allowed tags, from ``# raylint: allow(tag)``."""
        allows: Dict[int, Set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    m = _ALLOW_RE.search(tok.string)
                    if m:
                        tags = {t.strip() for t in m.group(1).split(",")}
                        allows.setdefault(tok.start[0], set()).update(tags)
        except tokenize.TokenError:
            pass
        return allows

    def allowed(self, line: int, rule_id: str, tag: str) -> bool:
        """A finding is suppressed by an allow comment on its own line, the
        line above, or the enclosing statement's first line (for multi-line
        statements the AST anchors mid-construct)."""
        for cand in (line, line - 1):
            tags = self.allow.get(cand)
            if tags and ({rule_id, tag, "all"} & tags):
                return True
        return False


# --------------------------------------------------------------------------
# rule registry

RULES: List[Tuple[str, str, Callable]] = []           # (id, tag, fn(ctx))
PROJECT_RULES: List[Tuple[str, str, Callable]] = []   # (id, tag, fn(ctxs, engine))


def rule(rule_id: str, tag: str):
    def deco(fn):
        RULES.append((rule_id, tag, fn))
        return fn
    return deco


def project_rule(rule_id: str, tag: str):
    def deco(fn):
        PROJECT_RULES.append((rule_id, tag, fn))
        return fn
    return deco


# --------------------------------------------------------------------------
# shared AST helpers

def _dotted(node: ast.AST) -> Optional[str]:
    """Best-effort dotted name for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_LOCKISH = re.compile(r"(^|[._])(lock|mutex|cv|cond|sem)", re.IGNORECASE)


def _is_lockish(expr_text: Optional[str]) -> bool:
    return bool(expr_text and _LOCKISH.search(expr_text))


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _walk_pruned(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested def/lambda bodies
    (those run in another context — executors, callbacks, later)."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _funcs_with_class(tree: ast.Module):
    """Yield (class_name_or_None, FunctionDef/AsyncFunctionDef)."""
    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child
                yield from walk(child, cls)
            else:
                yield from walk(child, cls)
    yield from walk(tree, None)


# --------------------------------------------------------------------------
# R1: blocking calls inside async def bodies

_BLOCKING_SLEEP = {"time.sleep", "sleep"}


@rule("R1", "async-blocking")
def check_async_blocking(ctx: FileContext) -> Iterator[Finding]:
    """An ``async def`` body must not make blocking calls: they stall the
    event loop the serve/router/long-poll layer multiplexes on.  Flags
    ``time.sleep``, ``Future.result()``, lock ``.acquire()`` with no
    timeout/non-blocking arg, and ``ray_tpu.get(...)``."""

    def scan(body_node, fname):
        for node in _walk_pruned(body_node):
            if not isinstance(node, ast.Call):
                continue
            dn = _dotted(node.func)
            if dn in _BLOCKING_SLEEP and (
                    dn != "sleep" or
                    ctx.from_imports.get("sleep") == "time"):
                yield node, f"blocking time.sleep() inside 'async def {fname}'"
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr == "result" and not node.args and \
                        not _has_kwarg(node, "timeout"):
                    yield node, (f"blocking Future.result() inside "
                                 f"'async def {fname}' — await it instead")
                elif attr == "acquire" and _is_lockish(_dotted(node.func.value)):
                    if not node.args and not (_has_kwarg(node, "timeout") or
                                              _has_kwarg(node, "blocking")):
                        yield node, (f"lock .acquire() with no timeout inside "
                                     f"'async def {fname}' can deadlock the "
                                     f"event loop")
                elif attr == "get" and _dotted(node.func.value) == "ray_tpu":
                    yield node, (f"blocking ray_tpu.get() inside "
                                 f"'async def {fname}' — resolve off-loop")
            elif isinstance(node.func, ast.Name) and node.func.id == "get" and \
                    ctx.from_imports.get("get", "").startswith("ray_tpu"):
                yield node, (f"blocking ray_tpu.get() inside "
                             f"'async def {fname}' — resolve off-loop")

    for _cls, fn in _funcs_with_class(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node, msg in scan(fn, fn.name):
            if not ctx.allowed(node.lineno, "R1", "async-blocking"):
                yield Finding("R1", "async-blocking", ctx.relpath,
                              node.lineno, msg)


# --------------------------------------------------------------------------
# R2: statically inconsistent lock order (project rule)

def _lock_identity(expr: ast.AST, cls: Optional[str]) -> Optional[str]:
    text = _dotted(expr)
    if not _is_lockish(text):
        return None
    if text.startswith("self."):
        return f"{cls or '?'}.{text[5:]}"
    return text


def _iter_with_pairs(ctx: FileContext):
    """Yield (outer_id, inner_id, lineno) for every nested lock ``with``."""
    for cls, fn in _funcs_with_class(ctx.tree):
        stack: List[str] = []

        def visit(node):
            pushed = 0
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _lock_identity(item.context_expr, cls)
                    if lid:
                        for outer in stack:
                            if outer != lid:
                                yield (outer, lid, node.lineno)
                        stack.append(lid)
                        pushed += 1
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # nested defs run elsewhere/later
                yield from visit(child)
            for _ in range(pushed):
                stack.pop()

        for item in visit(fn):
            yield item


@project_rule("R2", "lock-order")
def check_lock_order(ctxs: List[FileContext], _engine) -> Iterator[Finding]:
    """If lock A is ever taken while holding B *and* B while holding A,
    two threads interleaving those paths deadlock.  Lock identity is the
    attribute path qualified by class name (``Router._lock``), so the rule
    correlates orderings across files."""
    edges: Dict[Tuple[str, str], List[Tuple[FileContext, int]]] = {}
    for ctx in ctxs:
        for outer, inner, line in _iter_with_pairs(ctx):
            edges.setdefault((outer, inner), []).append((ctx, line))
    seen: Set[Tuple[str, str]] = set()
    for (a, b), sites in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in seen:
            seen.add((a, b))
            other = edges[(b, a)][0]
            for ctx, line in sites:
                if ctx.allowed(line, "R2", "lock-order"):
                    continue
                yield Finding(
                    "R2", "lock-order", ctx.relpath, line,
                    f"lock order {a} -> {b} here conflicts with "
                    f"{b} -> {a} at {other[0].relpath}:{other[1]} "
                    f"(potential deadlock)")


# --------------------------------------------------------------------------
# R3: unguarded cross-thread shared-state mutation

def _self_attr_writes(fn: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                yield t.attr, node


def _guarded_by_lock(fn: ast.AST, write: ast.AST) -> bool:
    """True if *write* sits inside a ``with <lock-ish>:`` in *fn*."""
    class Visitor(ast.NodeVisitor):
        def __init__(self):
            self.depth = 0
            self.guarded = False

        def visit_With(self, node):
            lockish = any(_is_lockish(_dotted(i.context_expr))
                          for i in node.items)
            self.depth += lockish
            self.generic_visit(node)
            self.depth -= lockish

        visit_AsyncWith = visit_With

        def generic_visit(self, node):
            if node is write and self.depth > 0:
                self.guarded = True
            super().generic_visit(node)

    v = Visitor()
    v.visit(fn)
    return v.guarded


@rule("R3", "unguarded-state")
def check_unguarded_state(ctx: FileContext) -> Iterator[Finding]:
    """Inside one class, an attribute REBOUND both by a thread-entry method
    (a ``threading.Thread(target=self.x)`` target, an executor-submitted
    method, or ``run`` of a Thread subclass) and by on-thread code has two
    concurrent writers; every such write must hold a lock.  Single-writer
    attributes (the daemon owns them) are fine — the GIL makes the store
    itself atomic, ordering is what needs the lock."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {n.name: n for n in node.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # 1. thread-entry methods
        entries: Set[str] = set()
        base_names = {_dotted(b) for b in node.bases}
        if {"threading.Thread", "Thread"} & base_names and "run" in methods:
            entries.add("run")
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            dn = _dotted(sub.func)
            cand = None
            if dn in ("threading.Thread", "Thread"):
                for kw in sub.keywords:
                    if kw.arg == "target":
                        cand = kw.value
            elif isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in ("submit", "call_soon_threadsafe"):
                cand = sub.args[0] if sub.args else None
            if isinstance(cand, ast.Attribute) and \
                    isinstance(cand.value, ast.Name) and \
                    cand.value.id == "self" and cand.attr in methods:
                entries.add(cand.attr)
        if not entries:
            continue
        # 2. close entries over same-class self.method() calls
        reach = set(entries)
        frontier = list(entries)
        while frontier:
            m = frontier.pop()
            for sub in ast.walk(methods[m]):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == "self" and \
                        sub.func.attr in methods and \
                        sub.func.attr not in reach:
                    reach.add(sub.func.attr)
                    frontier.append(sub.func.attr)
        # 3. writers per attribute, split by side of the thread boundary
        writes: Dict[str, List[Tuple[str, ast.AST]]] = {}
        for mname, fn in methods.items():
            if mname == "__init__":
                continue
            for attr, wnode in _self_attr_writes(fn):
                writes.setdefault(attr, []).append((mname, wnode))
        for attr, sites in sorted(writes.items()):
            owners = {m for m, _ in sites}
            off = owners & reach
            on = owners - reach
            if not off or not on:
                continue  # single side owns it
            for mname, wnode in sites:
                if _guarded_by_lock(methods[mname], wnode):
                    continue
                if ctx.allowed(wnode.lineno, "R3", "unguarded-state"):
                    continue
                side = "thread-entry" if mname in reach else "on-thread"
                yield Finding(
                    "R3", "unguarded-state", ctx.relpath, wnode.lineno,
                    f"self.{attr} written from {side} method "
                    f"'{mname}' without a lock, but also written from "
                    f"{'on-thread' if side == 'thread-entry' else 'thread-entry'}"
                    f" methods {sorted(on if side == 'thread-entry' else off)}"
                    f" of class {node.name}")


# --------------------------------------------------------------------------
# R4: silent exception swallows

_LOG_ATTRS = {"debug", "info", "warning", "warn", "error", "exception",
              "critical", "log", "record", "print_exc", "print_exception"}


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _LOG_ATTRS:
                return False
            if isinstance(fn, ast.Name) and fn.id in ("print", "warn"):
                return False
        if handler.name and isinstance(node, ast.Name) and \
                node.id == handler.name and isinstance(node.ctx, ast.Load):
            return False  # the exception object is used, not dropped
    return True


def _broad_catch(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    for t in types:
        if _dotted(t) in ("Exception", "BaseException"):
            return True
    return False


@rule("R4", "swallow")
def check_swallow(ctx: FileContext) -> Iterator[Finding]:
    """A broad ``except`` that neither re-raises, logs, nor *uses* the
    caught exception hides faults — exactly the ones chaos tests try to
    surface in daemon threads and RPC/scheduler/object-store paths.  Either
    handle it visibly or justify with ``# raylint: allow(swallow) <why>``."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_catch(node) or not _handler_is_silent(node):
            continue
        if ctx.allowed(node.lineno, "R4", "swallow"):
            continue
        yield Finding(
            "R4", "swallow", ctx.relpath, node.lineno,
            "broad except swallows the exception silently: re-raise, log "
            "with context, or justify with '# raylint: allow(swallow) <why>'")


# --------------------------------------------------------------------------
# R5: host-device sync reachable from jitted step functions

_SYNC_CALLS = {"jax.device_get", "device_get", "np.asarray", "numpy.asarray",
               "onp.asarray", "np.array", "numpy.array"}
_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pmap", "pmap",
              "jax.experimental.pjit.pjit"}
_TRACED_HOFS = {"jax.lax.scan", "lax.scan", "jax.lax.fori_loop",
                "lax.fori_loop", "jax.lax.while_loop", "lax.while_loop",
                "jax.lax.cond", "lax.cond", "jax.grad", "jax.value_and_grad",
                "jax.checkpoint", "jax.remat"}


def _jit_decorated(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        dn = _dotted(target)
        if dn in _JIT_NAMES:
            return True
        if dn in ("functools.partial", "partial") and \
                isinstance(dec, ast.Call) and dec.args and \
                _dotted(dec.args[0]) in _JIT_NAMES:
            return True
    return False


@rule("R5", "host-sync")
def check_host_sync(ctx: FileContext) -> Iterator[Finding]:
    """``.item()`` / ``float()`` / ``np.asarray`` / ``jax.device_get``
    inside a function reachable from a jitted train/bench step either
    fails tracing or — worse — silently forces a device→host sync per
    step.  Roots are jit/pmap-decorated functions and functions handed to
    ``jax.jit``/``lax.scan``-style tracers; reachability is the module-
    local call graph."""
    module_fns: Dict[str, ast.AST] = {}
    for _cls, fn in _funcs_with_class(ctx.tree):
        module_fns.setdefault(fn.name, fn)

    roots: Set[str] = set()
    for name, fn in module_fns.items():
        if _jit_decorated(fn):
            roots.add(name)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dn = _dotted(node.func)
        if dn in _JIT_NAMES and node.args:
            arg = _dotted(node.args[0])
            if arg in module_fns:
                roots.add(arg)
        elif dn in _TRACED_HOFS and node.args:
            arg = _dotted(node.args[0])
            if arg in module_fns:
                roots.add(arg)
    if not roots:
        return

    # module-local call-graph closure (plain Name calls only)
    reach = set(roots)
    frontier = list(roots)
    while frontier:
        fname = frontier.pop()
        for node in ast.walk(module_fns[fname]):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = node.func.id
                if callee in module_fns and callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)

    for fname in sorted(reach):
        fn = module_fns[fname]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            msg = None
            dn = _dotted(node.func)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                msg = ".item() forces a device->host sync"
            elif dn in _SYNC_CALLS:
                msg = f"{dn}() copies device data to host"
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int") and node.args and \
                    not isinstance(node.args[0], ast.Constant):
                msg = (f"{node.func.id}() on a traced value forces a "
                       f"device->host sync")
            if msg and not ctx.allowed(node.lineno, "R5", "host-sync"):
                yield Finding(
                    "R5", "host-sync", ctx.relpath, node.lineno,
                    f"{msg} inside '{fname}', reachable from jitted "
                    f"root(s) {sorted(roots & reach)}")


# --------------------------------------------------------------------------
# R7: hand-rolled retry loops (constant sleep + except in the same loop)

def _const_sleep_arg(node: ast.Call, ctx: FileContext) -> Optional[ast.AST]:
    """Return the argument node if *node* is a ``time.sleep(...)`` call,
    else None.  Accepts ``sleep`` imported from ``time``."""
    dn = _dotted(node.func)
    if dn == "time.sleep":
        pass
    elif dn == "sleep" and ctx.from_imports.get("sleep") == "time":
        pass
    else:
        return None
    return node.args[0] if node.args else None


@rule("R7", "bare-retry")
def check_bare_retry(ctx: FileContext) -> Iterator[Finding]:
    """A loop that catches exceptions and paces itself with a constant
    ``time.sleep`` is a hand-rolled retry: no jitter (thundering herd on
    recovery), no cap, no deadline budget.  That also covers the
    ``for delay in (0.1, 0.5, 2.0): ... sleep(delay)`` ladder — a
    hard-coded schedule with the same problems.  Use
    ``ray_tpu._private.backoff.BackoffPolicy`` / ``retry_call`` instead,
    or justify with ``# raylint: allow(bare-retry) <why>``."""

    def loop_const_names(loop: ast.AST) -> Set[str]:
        """Names bound by a ``for X in (const, ...)`` header."""
        if not isinstance(loop, ast.For):
            return set()
        it = loop.iter
        if isinstance(it, (ast.Tuple, ast.List)) and it.elts and \
                all(isinstance(e, ast.Constant) and
                    isinstance(e.value, (int, float)) for e in it.elts):
            if isinstance(loop.target, ast.Name):
                return {loop.target.id}
        return set()

    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.While, ast.For)):
            continue
        body_nodes = [n for stmt in loop.body for n in _walk_pruned(stmt)]
        if not any(isinstance(n, ast.ExceptHandler) for n in body_nodes):
            continue
        const_names = loop_const_names(loop)
        for node in body_nodes:
            if not isinstance(node, ast.Call):
                continue
            arg = _const_sleep_arg(node, ctx)
            if arg is None:
                continue
            constant = (
                isinstance(arg, ast.Constant) and
                isinstance(arg.value, (int, float))) or (
                isinstance(arg, ast.Name) and arg.id in const_names)
            if not constant:
                continue
            if ctx.allowed(node.lineno, "R7", "bare-retry"):
                continue
            yield Finding(
                "R7", "bare-retry", ctx.relpath, node.lineno,
                "constant time.sleep() paces a retry loop (loop also "
                "catches exceptions): no jitter, cap, or deadline — use "
                "ray_tpu._private.backoff.BackoffPolicy, or justify with "
                "'# raylint: allow(bare-retry) <why>'")


# --------------------------------------------------------------------------
# R8: hidden payload copies in hot-path (bulk-transfer) modules

_HOT_PATH_RE = re.compile(r"#\s*raylint:\s*hot-path")
_BUFFERISH_CALLS = {"memoryview", "bytearray"}


@rule("R8", "hidden-copy")
def check_hidden_copy(ctx: FileContext) -> Iterator[Finding]:
    """Inside a module annotated ``# raylint: hot-path`` (the payload
    plane: rpc / object transfer / store), a ``bytes(...)`` cast of a
    memoryview, bytearray, or slice duplicates payload bytes the zero-copy
    framing exists to avoid — and ``b"".join(chunks)`` is the classic
    reassembly copy (land chunks in a preallocated buffer instead).
    Metadata-sized casts are justified with
    ``# raylint: allow(hidden-copy) <why>``."""
    if not _HOT_PATH_RE.search(ctx.source):
        return
    # File-level approximation of buffer-ish bindings: any name ever
    # assigned from memoryview(...)/bytearray(...) counts everywhere —
    # hot-path modules are exactly where that heuristic is accurate.
    bufferish: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Name) \
                and node.value.func.id in _BUFFERISH_CALLS:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bufferish.add(t.id)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        msg = None
        if isinstance(node.func, ast.Name) and node.func.id == "bytes" \
                and len(node.args) == 1 and not node.keywords:
            arg = node.args[0]
            if isinstance(arg, ast.Subscript):
                msg = ("bytes(<slice>) materializes a payload copy — pass "
                       "the memoryview (or a gather list) through instead")
            elif isinstance(arg, ast.Call) and \
                    isinstance(arg.func, ast.Name) and \
                    arg.func.id in _BUFFERISH_CALLS:
                msg = (f"bytes({arg.func.id}(...)) copies the whole "
                       f"buffer — keep the view")
            elif isinstance(arg, ast.Name) and arg.id in bufferish:
                msg = (f"bytes({arg.id}) copies a buffer-backed value — "
                       f"keep the view or write into the destination")
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and \
                isinstance(node.func.value, ast.Constant) and \
                isinstance(node.func.value.value, bytes):
            msg = ("b\"\".join(...) reassembles chunks through an extra "
                   "copy — recv_into a preallocated destination instead")
        if msg and not ctx.allowed(node.lineno, "R8", "hidden-copy"):
            yield Finding("R8", "hidden-copy", ctx.relpath, node.lineno, msg)


# --------------------------------------------------------------------------
# R9: checkpoint directory I/O that bypasses the manifest commit path

_CKPT_IO_SCOPES = {"train", "tune", "serve"}
_CKPT_IO_METHODS = {"to_directory", "from_directory"}


@rule("R9", "direct-checkpoint-io")
def check_direct_checkpoint_io(ctx: FileContext) -> Iterator[Finding]:
    """In the train/tune/serve subtrees, ``Checkpoint.to_directory`` /
    ``from_directory`` write/read whole-value blobs with none of the
    engine's guarantees: no crash-atomic commit, no content dedup, no
    reshard-on-restore. Those layers must move checkpoints as manifest
    refs through ``ray_tpu.checkpoint``. The engine itself and ``air/``
    (the conversion layer) are out of scope; deliberate blob I/O is
    justified with ``# raylint: allow(direct-checkpoint-io) <why>``."""
    segments = set(ctx.relpath.replace("\\", "/").split("/")[:-1])
    if not segments & _CKPT_IO_SCOPES:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _CKPT_IO_METHODS):
            continue
        if ctx.allowed(node.lineno, "R9", "direct-checkpoint-io"):
            continue
        yield Finding(
            "R9", "direct-checkpoint-io", ctx.relpath, node.lineno,
            f".{node.func.attr}() bypasses the checkpoint engine's "
            "crash-atomic manifest commit — persist/restore through "
            "ray_tpu.checkpoint (manifest refs) instead")


# --------------------------------------------------------------------------
# R6: proto <-> pb2 wire-schema drift (project rule)

def parse_proto_text(source: str) -> Dict[str, Dict[str, int]]:
    """Parse message fields and enum values out of .proto text.

    Returns ``{"Msg": {"field": number}, "Enum": {"VALUE": number}}`` with
    nested messages flattened as ``Outer.Inner``.
    """
    src = re.sub(r"//[^\n]*", "", source)
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
    field_re = re.compile(
        r"(?:repeated\s+|optional\s+|required\s+)?"
        r"(?:map\s*<[^>]+>|[\w.]+)\s+(\w+)\s*=\s*(\d+)\s*(?:\[[^\]]*\])?\s*;$")
    enum_val_re = re.compile(r"(\w+)\s*=\s*(\d+)\s*;$")
    # one token per block open / close / terminated statement
    token_re = re.compile(
        r"\b(message|enum|oneof)\s+(\w+)\s*\{|(\{)|(\})|([^{};]+;)")
    out: Dict[str, Dict[str, int]] = {}
    stack: List[Tuple[str, str]] = []  # (kind, qualified name)

    for m in token_re.finditer(src):
        if m.group(1):
            kind, name = m.group(1), m.group(2)
            if kind == "oneof":
                # oneof members belong to the enclosing message
                stack.append(("oneof", stack[-1][1] if stack else name))
            else:
                parent = stack[-1][1] + "." if stack and \
                    stack[-1][0] == "message" else ""
                qual = parent + name
                out.setdefault(qual, {})
                stack.append((kind, qual))
        elif m.group(3):
            stack.append(("block", stack[-1][1] if stack else ""))
        elif m.group(4):
            if stack:
                stack.pop()
        elif stack:
            stmt = " ".join(m.group(5).split())
            kind, qual = stack[-1]
            if kind in ("message", "oneof"):
                fm = field_re.match(stmt)
                if fm:
                    out[qual][fm.group(1)] = int(fm.group(2))
            elif kind == "enum":
                em = enum_val_re.match(stmt)
                if em:
                    out[qual][em.group(1)] = int(em.group(2))
    return out


def parse_pb2_descriptor(pb2_source: str) -> Dict[str, Dict[str, int]]:
    """Extract the serialized FileDescriptorProto from generated pb2 source
    and flatten it to the same shape as :func:`parse_proto_text`.

    Works on the source text (no import), so fixture copies never collide
    with the process-wide protobuf descriptor pool.
    """
    tree = ast.parse(pb2_source)
    blob: Optional[bytes] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "AddSerializedFile" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, bytes):
            blob = node.args[0].value
            break
    if blob is None:
        raise ValueError("no AddSerializedFile(...) blob in pb2 source")
    from google.protobuf import descriptor_pb2
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.MergeFromString(blob)

    out: Dict[str, Dict[str, int]] = {}

    def walk_msg(msg, prefix):
        qual = prefix + msg.name
        fields = out.setdefault(qual, {})
        for f in msg.field:
            fields[f.name] = f.number
        for nested in msg.nested_type:
            if nested.options.map_entry:
                continue  # synthetic map<>-entry message
            walk_msg(nested, qual + ".")
        for enum in msg.enum_type:
            out[qual + "." + enum.name] = {v.name: v.number
                                           for v in enum.value}

    for msg in fdp.message_type:
        walk_msg(msg, "")
    for enum in fdp.enum_type:
        out[enum.name] = {v.name: v.number for v in enum.value}
    return out


@project_rule("R6", "proto-drift")
def check_proto_drift(ctxs: List[FileContext], engine) -> Iterator[Finding]:
    """The committed ``raytpu_pb2.py`` must agree with ``raytpu.proto`` on
    every field and enum number: daemons deserialize each other's frames by
    number, so silent drift corrupts the wire, not a test."""
    pairs = engine.proto_pairs
    if pairs is None:
        pairs = []
        for ctx in ctxs:
            if os.path.basename(ctx.path) != "raytpu_pb2.py":
                continue
            proto = os.path.join(os.path.dirname(ctx.path), "raytpu.proto")
            if os.path.exists(proto):
                pairs.append((proto, ctx.path, ctx.relpath))
    for proto_path, pb2_path, relpath in pairs:
        with open(proto_path, encoding="utf-8") as f:
            want = parse_proto_text(f.read())
        with open(pb2_path, encoding="utf-8") as f:
            got = parse_pb2_descriptor(f.read())
        for qual, fields in sorted(want.items()):
            if qual not in got:
                yield Finding("R6", "proto-drift", relpath, 1,
                              f"{qual} declared in raytpu.proto but absent "
                              f"from the generated pb2")
                continue
            for name, num in sorted(fields.items()):
                gnum = got[qual].get(name)
                if gnum is None:
                    yield Finding(
                        "R6", "proto-drift", relpath, 1,
                        f"{qual}.{name} (= {num}) missing from pb2 — "
                        f"run ray_tpu.protocol.regenerate()")
                elif gnum != num:
                    yield Finding(
                        "R6", "proto-drift", relpath, 1,
                        f"{qual}.{name}: proto says {num}, pb2 says {gnum} "
                        f"— wire numbers drifted, regenerate")
        for qual, fields in sorted(got.items()):
            for name in sorted(set(fields) - set(want.get(qual, {}))):
                yield Finding(
                    "R6", "proto-drift", relpath, 1,
                    f"{qual}.{name} present in pb2 but not in raytpu.proto")


# --------------------------------------------------------------------------
# engine

class LintEngine:
    def __init__(self, roots: Iterable[str], baseline_path: Optional[str] = None,
                 only_rules: Optional[Set[str]] = None,
                 proto_pairs: Optional[List[Tuple[str, str, str]]] = None):
        self.roots = [os.path.abspath(r) for r in roots]
        self.baseline = self._load_baseline(baseline_path)
        self.only_rules = only_rules
        # explicit (proto_path, pb2_path, relpath) triples override R6's
        # autodiscovery — the drift tests point this at mutated fixtures
        self.proto_pairs = proto_pairs
        self.errors: List[str] = []

    @staticmethod
    def _load_baseline(path: Optional[str]) -> Set[Tuple[str, str]]:
        entries: Set[Tuple[str, str]] = set()
        if not path or not os.path.exists(path):
            return entries
        with open(path, encoding="utf-8") as f:
            for raw in f:
                line = raw.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split(None, 1)
                if len(parts) == 2:
                    entries.add((parts[0], parts[1].strip()))
        return entries

    def _want(self, rule_id: str, tag: str) -> bool:
        return not self.only_rules or \
            bool({rule_id, tag} & self.only_rules)

    def _iter_files(self) -> Iterator[Tuple[str, str]]:
        for root in self.roots:
            if os.path.isfile(root):
                yield root, os.path.basename(root)
                continue
            base = os.path.dirname(root.rstrip(os.sep))
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__", ".git"))
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        full = os.path.join(dirpath, fname)
                        yield full, os.path.relpath(full, base)

    def run(self) -> List[Finding]:
        ctxs: List[FileContext] = []
        for path, rel in self._iter_files():
            try:
                with open(path, encoding="utf-8") as f:
                    ctxs.append(FileContext(path, rel, f.read()))
            except (SyntaxError, UnicodeDecodeError) as e:
                self.errors.append(f"{rel}: unparseable: {e}")
        findings: List[Finding] = []
        for ctx in ctxs:
            for rule_id, tag, fn in RULES:
                if self._want(rule_id, tag):
                    findings.extend(fn(ctx))
        for rule_id, tag, fn in PROJECT_RULES:
            if self._want(rule_id, tag):
                findings.extend(fn(ctxs, self))
        findings = [f for f in findings
                    if (f.rule, f.path) not in self.baseline]
        # nested loops can both see one sleep/handler — report each site once
        findings = sorted(set(findings),
                          key=lambda f: (f.path, f.line, f.rule))
        return findings


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="framework-aware static analysis for ray_tpu")
    parser.add_argument("roots", nargs="*", default=["ray_tpu"],
                        help="files or directories to lint")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as a JSON array")
    parser.add_argument("--baseline", default=None,
                        help="allowlist file of 'RULE path' lines")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids/tags to run "
                             "(default: all)")
    parser.add_argument("--write-baseline", default=None, metavar="FILE",
                        help="write current findings as a baseline and exit 0")
    args = parser.parse_args(argv)

    only = {r.strip() for r in args.rules.split(",")} if args.rules else None
    engine = LintEngine(args.roots or ["ray_tpu"], args.baseline, only)
    findings = engine.run()

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            f.write("# raylint baseline — tolerated pre-existing findings\n")
            for rule_id, path in sorted({(x.rule, x.path) for x in findings}):
                f.write(f"{rule_id} {path}\n")
        print(f"wrote {args.write_baseline} "
              f"({len(findings)} findings baselined)")
        return 0

    if args.json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
        print(f"raylint: {len(findings)} finding(s)"
              + (f" ({summary})" if summary else ""))
        for err in engine.errors:
            print(f"raylint: warning: {err}")
    return 1 if findings else 0
