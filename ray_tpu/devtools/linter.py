"""Framework-aware AST lint engine.

The engine walks every ``*.py`` file under the given roots, parses each
once, and runs two kinds of rules over the parse trees:

- **file rules** see one file at a time (R1, R3, R4, R5);
- **project rules** see the whole tree at once and can correlate across
  files (R2 lock-order consistency, R6 proto/pb2 drift).

Suppression has two layers, mirroring the sanitizer stance of the native
side (``run_sanitizers.sh``):

- an inline justification comment on the finding line or the line above::

      except Exception:  # raylint: allow(swallow) best-effort close
          pass

  The tag in ``allow(...)`` is the rule's short tag (``swallow``,
  ``lock-order``, ...) or its id (``R4``); ``allow(all)`` suppresses every
  rule on that line.  The justification text after the tag is *required
  culture*, not enforced syntax.
- a per-file allowlist baseline (``--baseline FILE``): lines of
  ``RULE<whitespace>path`` that tolerate pre-existing findings while a
  cleanup is in flight.  The shipped baseline is empty — the tree lints
  clean — and CI fails on any finding not covered by one of the two.

Rules (see ARCHITECTURE.md "Static analysis & concurrency invariants"):

==== ============== ====================================================
id   tag            what it catches
==== ============== ====================================================
R1   async-blocking blocking call (``time.sleep``, ``.result()``,
                    lock ``.acquire()`` without timeout, ``ray_tpu.get``)
                    inside an ``async def`` body
R2   lock-order     two locks statically acquired in both A→B and B→A
                    nesting orders anywhere in the tree
R3   unguarded-state self-attribute written both from a thread-entry
                    method and from on-thread code with no lock held
R4   swallow        ``except Exception:`` that neither re-raises, logs,
                    nor uses the caught exception
R5   host-sync      host-device sync (``.item()``, ``float()``,
                    ``np.asarray``, ``jax.device_get``) reachable from a
                    jitted step function
R6   proto-drift    field/enum-number drift between ``raytpu.proto`` and
                    the committed ``raytpu_pb2.py``
R7   bare-retry     hand-rolled retry loop: constant ``time.sleep`` inside
                    a loop that also catches exceptions (use
                    ``ray_tpu._private.backoff.BackoffPolicy``)
R8   hidden-copy    ``bytes(<memoryview/bytearray/slice>)`` casts and
                    ``b"".join`` chunk reassembly inside modules marked
                    ``# raylint: hot-path`` (payload-plane copies the
                    zero-copy data plane exists to eliminate)
R9   direct-checkpoint-io
                    ``.to_directory()`` / ``.from_directory()`` calls in
                    the ``train/``, ``tune/`` or ``serve/`` subtrees —
                    directory blobs bypass the checkpoint engine's
                    crash-atomic manifest commit; go through
                    ``ray_tpu.checkpoint`` (the engine itself and
                    ``air/`` are out of scope)
R10  async-transitive
                    a blocking primitive (R1's set) reachable from an
                    ``async def`` through the whole-program call graph —
                    the interprocedural closure of R1
R11  lock-order-graph
                    lock acquisitions collected across function
                    boundaries into one global order graph; cycles are
                    reported with the full call path and in lockwatch's
                    runtime cycle format
R12  collective-divergence
                    a collective/barrier/checkpoint-commit call (direct
                    or transitive) dominated by a branch on rank-,
                    world-size-, or local-exception-dependent state —
                    the classic SPMD deadlock
R13  config-drift   every config knob must be read somewhere and every
                    ``_config.<name>`` read must be defined; same
                    closure for chaos points declared in the runtime
                    vs. exercised by ``tests/``
R14  span-leak      ``observability.span(...)`` used outside a ``with``
                    statement (outside the observability package): a
                    span not closed on every exit path leaks its
                    context var and never records
R15  metrics-cardinality
                    a metric tag value derived from unbounded runtime
                    data (object/task/trace ids, raw peer addresses):
                    every entity mints a new time series, growing the
                    registry and every scrape without bound
R16  resource-leak  an OS-backed resource (socket, file, mmap,
                    non-daemon thread, executor) acquired on some path
                    but neither released nor ownership-transferred
                    before the function exits on that path (incl.
                    ``__init__`` aborts); dynamic handoffs are asserted
                    with ``# raylint: transfer(<kind>) <why>``
R17  deadline-drop  a blocking primitive with no bound (bare
                    ``.wait()``/``.join()``/``.acquire()``/``.get()``,
                    ``.result()`` without timeout) reachable over call
                    edges from a deadline-scoped entry point — the
                    budget the caller was promised is silently dropped
R18  protocol       RPC vocabulary + lifecycle conformance: every sent
                    ``pb.<METHOD>`` has a dispatch arm and vice versa,
                    handlers reply exactly once per completed path, and
                    every static ``.state = "<STATE>"`` write is a
                    transition ``dataflow.NODE_LIFECYCLE`` declares
==== ============== ====================================================

R10-R12 run on the whole-program call graph built by
:mod:`ray_tpu.devtools.callgraph`; unresolvable dynamic calls degrade to
"unknown" (no edges), so the interprocedural rules can under-report but
never invent a path.  R16-R18 add the path-sensitive layer in
:mod:`ray_tpu.devtools.dataflow` on top of that graph — same
under-approximation stance, with witness paths kept for messages.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import sys
import tempfile
import time
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools import callgraph as _cg
from ray_tpu.devtools import dataflow as _df
from ray_tpu.devtools import shardprop as _sp

__all__ = ["Finding", "LintEngine", "rule", "project_rule", "RULES",
           "PROJECT_RULES", "rule_listing"]

_ALLOW_RE = re.compile(r"#\s*raylint:\s*allow\(([A-Za-z0-9_,\- ]+)\)")


@dataclass(frozen=True)
class Finding:
    rule: str          # "R4"
    tag: str           # "swallow"
    path: str          # path relative to the lint root
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}({self.tag}): {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "tag": self.tag, "path": self.path,
                "line": self.line, "message": self.message}


class FileContext:
    """One parsed source file plus the lookups rules keep re-needing."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.allow = self._collect_allows(source)
        # name -> module it was imported from ("from ray_tpu import get")
        self.from_imports: Dict[str, str] = {}
        # name -> fully-qualified origin ("from ray_tpu import chaos as ch"
        # binds ch -> "ray_tpu.chaos"; "import ray_tpu.chaos as ch" likewise)
        self.import_origin: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    bound = alias.asname or alias.name
                    self.from_imports[bound] = node.module
                    self.import_origin[bound] = \
                        node.module + "." + alias.name
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.import_origin[alias.asname] = alias.name
                    else:
                        top = alias.name.split(".")[0]
                        self.import_origin[top] = top

    @staticmethod
    def _collect_allows(source: str) -> Dict[int, Set[str]]:
        """line -> set of allowed tags, from ``# raylint: allow(tag)``."""
        allows: Dict[int, Set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    m = _ALLOW_RE.search(tok.string)
                    if m:
                        tags = {t.strip() for t in m.group(1).split(",")}
                        allows.setdefault(tok.start[0], set()).update(tags)
        except tokenize.TokenError:
            pass
        return allows

    def allowed(self, line: int, rule_id: str, tag: str) -> bool:
        """A finding is suppressed by an allow comment on its own line, the
        line above, or the enclosing statement's first line (for multi-line
        statements the AST anchors mid-construct)."""
        for cand in (line, line - 1):
            tags = self.allow.get(cand)
            if tags and ({rule_id, tag, "all"} & tags):
                return True
        return False


# --------------------------------------------------------------------------
# rule registry

RULES: List[Tuple[str, str, Callable]] = []           # (id, tag, fn(ctx))
PROJECT_RULES: List[Tuple[str, str, Callable]] = []   # (id, tag, fn(ctxs, engine))


def rule(rule_id: str, tag: str):
    def deco(fn):
        RULES.append((rule_id, tag, fn))
        return fn
    return deco


def project_rule(rule_id: str, tag: str):
    def deco(fn):
        PROJECT_RULES.append((rule_id, tag, fn))
        return fn
    return deco


# --------------------------------------------------------------------------
# shared AST helpers

def _dotted(node: ast.AST) -> Optional[str]:
    """Best-effort dotted name for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_LOCKISH = re.compile(r"(^|[._])(lock|mutex|cv|cond|sem)", re.IGNORECASE)


def _is_lockish(expr_text: Optional[str]) -> bool:
    return bool(expr_text and _LOCKISH.search(expr_text))


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _walk_pruned(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested def/lambda bodies
    (those run in another context — executors, callbacks, later)."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _funcs_with_class(tree: ast.Module):
    """Yield (class_name_or_None, FunctionDef/AsyncFunctionDef)."""
    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child
                yield from walk(child, cls)
            else:
                yield from walk(child, cls)
    yield from walk(tree, None)


# --------------------------------------------------------------------------
# R1: blocking calls inside async def bodies

_BLOCKING_SLEEP = {"time.sleep", "sleep"}


@rule("R1", "async-blocking")
def check_async_blocking(ctx: FileContext) -> Iterator[Finding]:
    """An ``async def`` body must not make blocking calls: they stall the
    event loop the serve/router/long-poll layer multiplexes on.  Flags
    ``time.sleep``, ``Future.result()``, lock ``.acquire()`` with no
    timeout/non-blocking arg, and ``ray_tpu.get(...)``."""

    def scan(body_node, fname):
        for node in _walk_pruned(body_node):
            if not isinstance(node, ast.Call):
                continue
            dn = _dotted(node.func)
            if dn in _BLOCKING_SLEEP and (
                    dn != "sleep" or
                    ctx.from_imports.get("sleep") == "time"):
                yield node, f"blocking time.sleep() inside 'async def {fname}'"
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr == "result" and not node.args and \
                        not _has_kwarg(node, "timeout"):
                    yield node, (f"blocking Future.result() inside "
                                 f"'async def {fname}' — await it instead")
                elif attr == "acquire" and _is_lockish(_dotted(node.func.value)):
                    if not node.args and not (_has_kwarg(node, "timeout") or
                                              _has_kwarg(node, "blocking")):
                        yield node, (f"lock .acquire() with no timeout inside "
                                     f"'async def {fname}' can deadlock the "
                                     f"event loop")
                elif attr == "get" and _dotted(node.func.value) == "ray_tpu":
                    yield node, (f"blocking ray_tpu.get() inside "
                                 f"'async def {fname}' — resolve off-loop")
            elif isinstance(node.func, ast.Name) and node.func.id == "get" and \
                    ctx.from_imports.get("get", "").startswith("ray_tpu"):
                yield node, (f"blocking ray_tpu.get() inside "
                             f"'async def {fname}' — resolve off-loop")

    for _cls, fn in _funcs_with_class(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node, msg in scan(fn, fn.name):
            if not ctx.allowed(node.lineno, "R1", "async-blocking"):
                yield Finding("R1", "async-blocking", ctx.relpath,
                              node.lineno, msg)


# --------------------------------------------------------------------------
# R2: statically inconsistent lock order (project rule)

def _lock_identity(expr: ast.AST, cls: Optional[str]) -> Optional[str]:
    text = _dotted(expr)
    if not _is_lockish(text):
        return None
    if text.startswith("self."):
        return f"{cls or '?'}.{text[5:]}"
    return text


def _iter_with_pairs(ctx: FileContext):
    """Yield (outer_id, inner_id, lineno) for every nested lock ``with``."""
    for cls, fn in _funcs_with_class(ctx.tree):
        stack: List[str] = []

        def visit(node):
            pushed = 0
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _lock_identity(item.context_expr, cls)
                    if lid:
                        for outer in stack:
                            if outer != lid:
                                yield (outer, lid, node.lineno)
                        stack.append(lid)
                        pushed += 1
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # nested defs run elsewhere/later
                yield from visit(child)
            for _ in range(pushed):
                stack.pop()

        for item in visit(fn):
            yield item


@project_rule("R2", "lock-order")
def check_lock_order(ctxs: List[FileContext], _engine) -> Iterator[Finding]:
    """If lock A is ever taken while holding B *and* B while holding A,
    two threads interleaving those paths deadlock.  Lock identity is the
    attribute path qualified by class name (``Router._lock``), so the rule
    correlates orderings across files."""
    edges: Dict[Tuple[str, str], List[Tuple[FileContext, int]]] = {}
    for ctx in ctxs:
        for outer, inner, line in _iter_with_pairs(ctx):
            edges.setdefault((outer, inner), []).append((ctx, line))
    seen: Set[Tuple[str, str]] = set()
    for (a, b), sites in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in seen:
            seen.add((a, b))
            other = edges[(b, a)][0]
            for ctx, line in sites:
                if ctx.allowed(line, "R2", "lock-order"):
                    continue
                yield Finding(
                    "R2", "lock-order", ctx.relpath, line,
                    f"lock order {a} -> {b} here conflicts with "
                    f"{b} -> {a} at {other[0].relpath}:{other[1]} "
                    f"(potential deadlock)")


# --------------------------------------------------------------------------
# R3: unguarded cross-thread shared-state mutation

def _self_attr_writes(fn: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                yield t.attr, node


def _guarded_by_lock(fn: ast.AST, write: ast.AST) -> bool:
    """True if *write* sits inside a ``with <lock-ish>:`` in *fn*."""
    class Visitor(ast.NodeVisitor):
        def __init__(self):
            self.depth = 0
            self.guarded = False

        def visit_With(self, node):
            lockish = any(_is_lockish(_dotted(i.context_expr))
                          for i in node.items)
            self.depth += lockish
            self.generic_visit(node)
            self.depth -= lockish

        visit_AsyncWith = visit_With

        def generic_visit(self, node):
            if node is write and self.depth > 0:
                self.guarded = True
            super().generic_visit(node)

    v = Visitor()
    v.visit(fn)
    return v.guarded


@rule("R3", "unguarded-state")
def check_unguarded_state(ctx: FileContext) -> Iterator[Finding]:
    """Inside one class, an attribute REBOUND both by a thread-entry method
    (a ``threading.Thread(target=self.x)`` target, an executor-submitted
    method, or ``run`` of a Thread subclass) and by on-thread code has two
    concurrent writers; every such write must hold a lock.  Single-writer
    attributes (the daemon owns them) are fine — the GIL makes the store
    itself atomic, ordering is what needs the lock."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {n.name: n for n in node.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # 1. thread-entry methods
        entries: Set[str] = set()
        base_names = {_dotted(b) for b in node.bases}
        if {"threading.Thread", "Thread"} & base_names and "run" in methods:
            entries.add("run")
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            dn = _dotted(sub.func)
            cand = None
            if dn in ("threading.Thread", "Thread"):
                for kw in sub.keywords:
                    if kw.arg == "target":
                        cand = kw.value
            elif isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in ("submit", "call_soon_threadsafe"):
                cand = sub.args[0] if sub.args else None
            if isinstance(cand, ast.Attribute) and \
                    isinstance(cand.value, ast.Name) and \
                    cand.value.id == "self" and cand.attr in methods:
                entries.add(cand.attr)
        if not entries:
            continue
        # 2. close entries over same-class self.method() calls
        reach = set(entries)
        frontier = list(entries)
        while frontier:
            m = frontier.pop()
            for sub in ast.walk(methods[m]):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == "self" and \
                        sub.func.attr in methods and \
                        sub.func.attr not in reach:
                    reach.add(sub.func.attr)
                    frontier.append(sub.func.attr)
        # 3. writers per attribute, split by side of the thread boundary
        writes: Dict[str, List[Tuple[str, ast.AST]]] = {}
        for mname, fn in methods.items():
            if mname == "__init__":
                continue
            for attr, wnode in _self_attr_writes(fn):
                writes.setdefault(attr, []).append((mname, wnode))
        for attr, sites in sorted(writes.items()):
            owners = {m for m, _ in sites}
            off = owners & reach
            on = owners - reach
            if not off or not on:
                continue  # single side owns it
            for mname, wnode in sites:
                if _guarded_by_lock(methods[mname], wnode):
                    continue
                if ctx.allowed(wnode.lineno, "R3", "unguarded-state"):
                    continue
                side = "thread-entry" if mname in reach else "on-thread"
                yield Finding(
                    "R3", "unguarded-state", ctx.relpath, wnode.lineno,
                    f"self.{attr} written from {side} method "
                    f"'{mname}' without a lock, but also written from "
                    f"{'on-thread' if side == 'thread-entry' else 'thread-entry'}"
                    f" methods {sorted(on if side == 'thread-entry' else off)}"
                    f" of class {node.name}")


# --------------------------------------------------------------------------
# R4: silent exception swallows

_LOG_ATTRS = {"debug", "info", "warning", "warn", "error", "exception",
              "critical", "log", "record", "print_exc", "print_exception"}


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _LOG_ATTRS:
                return False
            if isinstance(fn, ast.Name) and fn.id in ("print", "warn"):
                return False
        if handler.name and isinstance(node, ast.Name) and \
                node.id == handler.name and isinstance(node.ctx, ast.Load):
            return False  # the exception object is used, not dropped
    return True


def _broad_catch(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    for t in types:
        if _dotted(t) in ("Exception", "BaseException"):
            return True
    return False


@rule("R4", "swallow")
def check_swallow(ctx: FileContext) -> Iterator[Finding]:
    """A broad ``except`` that neither re-raises, logs, nor *uses* the
    caught exception hides faults — exactly the ones chaos tests try to
    surface in daemon threads and RPC/scheduler/object-store paths.  Either
    handle it visibly or justify with ``# raylint: allow(swallow) <why>``."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_catch(node) or not _handler_is_silent(node):
            continue
        if ctx.allowed(node.lineno, "R4", "swallow"):
            continue
        yield Finding(
            "R4", "swallow", ctx.relpath, node.lineno,
            "broad except swallows the exception silently: re-raise, log "
            "with context, or justify with '# raylint: allow(swallow) <why>'")


# --------------------------------------------------------------------------
# R5: host-device sync reachable from jitted step functions

_SYNC_CALLS = {"jax.device_get", "device_get", "np.asarray", "numpy.asarray",
               "onp.asarray", "np.array", "numpy.array"}
_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pmap", "pmap",
              "jax.experimental.pjit.pjit"}
_TRACED_HOFS = {"jax.lax.scan", "lax.scan", "jax.lax.fori_loop",
                "lax.fori_loop", "jax.lax.while_loop", "lax.while_loop",
                "jax.lax.cond", "lax.cond", "jax.grad", "jax.value_and_grad",
                "jax.checkpoint", "jax.remat"}


def _jit_decorated(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        dn = _dotted(target)
        if dn in _JIT_NAMES:
            return True
        if dn in ("functools.partial", "partial") and \
                isinstance(dec, ast.Call) and dec.args and \
                _dotted(dec.args[0]) in _JIT_NAMES:
            return True
    return False


@rule("R5", "host-sync")
def check_host_sync(ctx: FileContext) -> Iterator[Finding]:
    """``.item()`` / ``float()`` / ``np.asarray`` / ``jax.device_get``
    inside a function reachable from a jitted train/bench step either
    fails tracing or — worse — silently forces a device→host sync per
    step.  Roots are jit/pmap-decorated functions and functions handed to
    ``jax.jit``/``lax.scan``-style tracers; reachability is the module-
    local call graph."""
    module_fns: Dict[str, ast.AST] = {}
    for _cls, fn in _funcs_with_class(ctx.tree):
        module_fns.setdefault(fn.name, fn)

    roots: Set[str] = set()
    for name, fn in module_fns.items():
        if _jit_decorated(fn):
            roots.add(name)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dn = _dotted(node.func)
        if dn in _JIT_NAMES and node.args:
            arg = _dotted(node.args[0])
            if arg in module_fns:
                roots.add(arg)
        elif dn in _TRACED_HOFS and node.args:
            arg = _dotted(node.args[0])
            if arg in module_fns:
                roots.add(arg)
    if not roots:
        return

    # module-local call-graph closure (plain Name calls only)
    reach = set(roots)
    frontier = list(roots)
    while frontier:
        fname = frontier.pop()
        for node in ast.walk(module_fns[fname]):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = node.func.id
                if callee in module_fns and callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)

    for fname in sorted(reach):
        fn = module_fns[fname]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            msg = None
            dn = _dotted(node.func)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                msg = ".item() forces a device->host sync"
            elif dn in _SYNC_CALLS:
                msg = f"{dn}() copies device data to host"
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int") and node.args and \
                    not isinstance(node.args[0], ast.Constant):
                msg = (f"{node.func.id}() on a traced value forces a "
                       f"device->host sync")
            if msg and not ctx.allowed(node.lineno, "R5", "host-sync"):
                yield Finding(
                    "R5", "host-sync", ctx.relpath, node.lineno,
                    f"{msg} inside '{fname}', reachable from jitted "
                    f"root(s) {sorted(roots & reach)}")


# --------------------------------------------------------------------------
# R7: hand-rolled retry loops (constant sleep + except in the same loop)

def _const_sleep_arg(node: ast.Call, ctx: FileContext) -> Optional[ast.AST]:
    """Return the argument node if *node* is a ``time.sleep(...)`` call,
    else None.  Accepts ``sleep`` imported from ``time``."""
    dn = _dotted(node.func)
    if dn == "time.sleep":
        pass
    elif dn == "sleep" and ctx.from_imports.get("sleep") == "time":
        pass
    else:
        return None
    return node.args[0] if node.args else None


@rule("R7", "bare-retry")
def check_bare_retry(ctx: FileContext) -> Iterator[Finding]:
    """A loop that catches exceptions and paces itself with a constant
    ``time.sleep`` is a hand-rolled retry: no jitter (thundering herd on
    recovery), no cap, no deadline budget.  That also covers the
    ``for delay in (0.1, 0.5, 2.0): ... sleep(delay)`` ladder — a
    hard-coded schedule with the same problems.  Use
    ``ray_tpu._private.backoff.BackoffPolicy`` / ``retry_call`` instead,
    or justify with ``# raylint: allow(bare-retry) <why>``."""

    def loop_const_names(loop: ast.AST) -> Set[str]:
        """Names bound by a ``for X in (const, ...)`` header."""
        if not isinstance(loop, ast.For):
            return set()
        it = loop.iter
        if isinstance(it, (ast.Tuple, ast.List)) and it.elts and \
                all(isinstance(e, ast.Constant) and
                    isinstance(e.value, (int, float)) for e in it.elts):
            if isinstance(loop.target, ast.Name):
                return {loop.target.id}
        return set()

    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.While, ast.For)):
            continue
        body_nodes = [n for stmt in loop.body for n in _walk_pruned(stmt)]
        if not any(isinstance(n, ast.ExceptHandler) for n in body_nodes):
            continue
        const_names = loop_const_names(loop)
        for node in body_nodes:
            if not isinstance(node, ast.Call):
                continue
            arg = _const_sleep_arg(node, ctx)
            if arg is None:
                continue
            constant = (
                isinstance(arg, ast.Constant) and
                isinstance(arg.value, (int, float))) or (
                isinstance(arg, ast.Name) and arg.id in const_names)
            if not constant:
                continue
            if ctx.allowed(node.lineno, "R7", "bare-retry"):
                continue
            yield Finding(
                "R7", "bare-retry", ctx.relpath, node.lineno,
                "constant time.sleep() paces a retry loop (loop also "
                "catches exceptions): no jitter, cap, or deadline — use "
                "ray_tpu._private.backoff.BackoffPolicy, or justify with "
                "'# raylint: allow(bare-retry) <why>'")


# --------------------------------------------------------------------------
# R8: hidden payload copies in hot-path (bulk-transfer) modules

_HOT_PATH_RE = re.compile(r"#\s*raylint:\s*hot-path")
_BUFFERISH_CALLS = {"memoryview", "bytearray"}


@rule("R8", "hidden-copy")
def check_hidden_copy(ctx: FileContext) -> Iterator[Finding]:
    """Inside a module annotated ``# raylint: hot-path`` (the payload
    plane: rpc / object transfer / store), a ``bytes(...)`` cast of a
    memoryview, bytearray, or slice duplicates payload bytes the zero-copy
    framing exists to avoid — and ``b"".join(chunks)`` is the classic
    reassembly copy (land chunks in a preallocated buffer instead).
    Metadata-sized casts are justified with
    ``# raylint: allow(hidden-copy) <why>``."""
    if not _HOT_PATH_RE.search(ctx.source):
        return
    # File-level approximation of buffer-ish bindings: any name ever
    # assigned from memoryview(...)/bytearray(...) counts everywhere —
    # hot-path modules are exactly where that heuristic is accurate.
    bufferish: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Name) \
                and node.value.func.id in _BUFFERISH_CALLS:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bufferish.add(t.id)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        msg = None
        if isinstance(node.func, ast.Name) and node.func.id == "bytes" \
                and len(node.args) == 1 and not node.keywords:
            arg = node.args[0]
            if isinstance(arg, ast.Subscript):
                msg = ("bytes(<slice>) materializes a payload copy — pass "
                       "the memoryview (or a gather list) through instead")
            elif isinstance(arg, ast.Call) and \
                    isinstance(arg.func, ast.Name) and \
                    arg.func.id in _BUFFERISH_CALLS:
                msg = (f"bytes({arg.func.id}(...)) copies the whole "
                       f"buffer — keep the view")
            elif isinstance(arg, ast.Name) and arg.id in bufferish:
                msg = (f"bytes({arg.id}) copies a buffer-backed value — "
                       f"keep the view or write into the destination")
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and \
                isinstance(node.func.value, ast.Constant) and \
                isinstance(node.func.value.value, bytes):
            msg = ("b\"\".join(...) reassembles chunks through an extra "
                   "copy — recv_into a preallocated destination instead")
        if msg and not ctx.allowed(node.lineno, "R8", "hidden-copy"):
            yield Finding("R8", "hidden-copy", ctx.relpath, node.lineno, msg)


# --------------------------------------------------------------------------
# R9: checkpoint directory I/O that bypasses the manifest commit path

_CKPT_IO_SCOPES = {"train", "tune", "serve"}
_CKPT_IO_METHODS = {"to_directory", "from_directory"}


@rule("R9", "direct-checkpoint-io")
def check_direct_checkpoint_io(ctx: FileContext) -> Iterator[Finding]:
    """In the train/tune/serve subtrees, ``Checkpoint.to_directory`` /
    ``from_directory`` write/read whole-value blobs with none of the
    engine's guarantees: no crash-atomic commit, no content dedup, no
    reshard-on-restore. Those layers must move checkpoints as manifest
    refs through ``ray_tpu.checkpoint``. The engine itself and ``air/``
    (the conversion layer) are out of scope; deliberate blob I/O is
    justified with ``# raylint: allow(direct-checkpoint-io) <why>``."""
    segments = set(ctx.relpath.replace("\\", "/").split("/")[:-1])
    if not segments & _CKPT_IO_SCOPES:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _CKPT_IO_METHODS):
            continue
        if ctx.allowed(node.lineno, "R9", "direct-checkpoint-io"):
            continue
        yield Finding(
            "R9", "direct-checkpoint-io", ctx.relpath, node.lineno,
            f".{node.func.attr}() bypasses the checkpoint engine's "
            "crash-atomic manifest commit — persist/restore through "
            "ray_tpu.checkpoint (manifest refs) instead")


# --------------------------------------------------------------------------
# R6: proto <-> pb2 wire-schema drift (project rule)

def parse_proto_text(source: str) -> Dict[str, Dict[str, int]]:
    """Parse message fields and enum values out of .proto text.

    Returns ``{"Msg": {"field": number}, "Enum": {"VALUE": number}}`` with
    nested messages flattened as ``Outer.Inner``.
    """
    src = re.sub(r"//[^\n]*", "", source)
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
    field_re = re.compile(
        r"(?:repeated\s+|optional\s+|required\s+)?"
        r"(?:map\s*<[^>]+>|[\w.]+)\s+(\w+)\s*=\s*(\d+)\s*(?:\[[^\]]*\])?\s*;$")
    enum_val_re = re.compile(r"(\w+)\s*=\s*(\d+)\s*;$")
    # one token per block open / close / terminated statement
    token_re = re.compile(
        r"\b(message|enum|oneof)\s+(\w+)\s*\{|(\{)|(\})|([^{};]+;)")
    out: Dict[str, Dict[str, int]] = {}
    stack: List[Tuple[str, str]] = []  # (kind, qualified name)

    for m in token_re.finditer(src):
        if m.group(1):
            kind, name = m.group(1), m.group(2)
            if kind == "oneof":
                # oneof members belong to the enclosing message
                stack.append(("oneof", stack[-1][1] if stack else name))
            else:
                parent = stack[-1][1] + "." if stack and \
                    stack[-1][0] == "message" else ""
                qual = parent + name
                out.setdefault(qual, {})
                stack.append((kind, qual))
        elif m.group(3):
            stack.append(("block", stack[-1][1] if stack else ""))
        elif m.group(4):
            if stack:
                stack.pop()
        elif stack:
            stmt = " ".join(m.group(5).split())
            kind, qual = stack[-1]
            if kind in ("message", "oneof"):
                fm = field_re.match(stmt)
                if fm:
                    out[qual][fm.group(1)] = int(fm.group(2))
            elif kind == "enum":
                em = enum_val_re.match(stmt)
                if em:
                    out[qual][em.group(1)] = int(em.group(2))
    return out


def parse_pb2_descriptor(pb2_source: str) -> Dict[str, Dict[str, int]]:
    """Extract the serialized FileDescriptorProto from generated pb2 source
    and flatten it to the same shape as :func:`parse_proto_text`.

    Works on the source text (no import), so fixture copies never collide
    with the process-wide protobuf descriptor pool.
    """
    tree = ast.parse(pb2_source)
    blob: Optional[bytes] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "AddSerializedFile" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, bytes):
            blob = node.args[0].value
            break
    if blob is None:
        raise ValueError("no AddSerializedFile(...) blob in pb2 source")
    from google.protobuf import descriptor_pb2
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.MergeFromString(blob)

    out: Dict[str, Dict[str, int]] = {}

    def walk_msg(msg, prefix):
        qual = prefix + msg.name
        fields = out.setdefault(qual, {})
        for f in msg.field:
            fields[f.name] = f.number
        for nested in msg.nested_type:
            if nested.options.map_entry:
                continue  # synthetic map<>-entry message
            walk_msg(nested, qual + ".")
        for enum in msg.enum_type:
            out[qual + "." + enum.name] = {v.name: v.number
                                           for v in enum.value}

    for msg in fdp.message_type:
        walk_msg(msg, "")
    for enum in fdp.enum_type:
        out[enum.name] = {v.name: v.number for v in enum.value}
    return out


@project_rule("R6", "proto-drift")
def check_proto_drift(ctxs: List[FileContext], engine) -> Iterator[Finding]:
    """The committed ``raytpu_pb2.py`` must agree with ``raytpu.proto`` on
    every field and enum number: daemons deserialize each other's frames by
    number, so silent drift corrupts the wire, not a test."""
    pairs = engine.proto_pairs
    if pairs is None:
        pairs = []
        for ctx in ctxs:
            if os.path.basename(ctx.path) != "raytpu_pb2.py":
                continue
            proto = os.path.join(os.path.dirname(ctx.path), "raytpu.proto")
            if os.path.exists(proto):
                pairs.append((proto, ctx.path, ctx.relpath))
    for proto_path, pb2_path, relpath in pairs:
        with open(proto_path, encoding="utf-8") as f:
            want = parse_proto_text(f.read())
        with open(pb2_path, encoding="utf-8") as f:
            got = parse_pb2_descriptor(f.read())
        for qual, fields in sorted(want.items()):
            if qual not in got:
                yield Finding("R6", "proto-drift", relpath, 1,
                              f"{qual} declared in raytpu.proto but absent "
                              f"from the generated pb2")
                continue
            for name, num in sorted(fields.items()):
                gnum = got[qual].get(name)
                if gnum is None:
                    yield Finding(
                        "R6", "proto-drift", relpath, 1,
                        f"{qual}.{name} (= {num}) missing from pb2 — "
                        f"run ray_tpu.protocol.regenerate()")
                elif gnum != num:
                    yield Finding(
                        "R6", "proto-drift", relpath, 1,
                        f"{qual}.{name}: proto says {num}, pb2 says {gnum} "
                        f"— wire numbers drifted, regenerate")
        for qual, fields in sorted(got.items()):
            for name in sorted(set(fields) - set(want.get(qual, {}))):
                yield Finding(
                    "R6", "proto-drift", relpath, 1,
                    f"{qual}.{name} present in pb2 but not in raytpu.proto")


# --------------------------------------------------------------------------
# R10: blocking primitives reachable from async defs (whole-program)

@project_rule("R10", "async-transitive")
def check_async_transitive(ctxs: List[FileContext],
                           engine) -> Iterator[Finding]:
    """R1 catches ``time.sleep`` written *inside* an ``async def``; this is
    its interprocedural closure: a blocking primitive (R1's set) anywhere
    in the synchronous call graph below an async function still stalls the
    event loop.  Propagation follows ``call`` edges and ``loop`` edges
    (``asyncio.create_task`` coroutines run on the same loop) but never
    ``spawn`` edges (thread targets / executor submissions run off-loop).
    Blocking sites written directly in an ``async def`` body are R1's job
    and are not re-reported here."""
    idx = engine.index(ctxs)
    direct: Dict[str, List[Tuple[int, Tuple[str, int, str]]]] = {}
    for q, fn in idx.functions.items():
        if fn.synthetic:
            continue              # arm statements belong to the dispatcher
        for line, desc in fn.blocking:
            direct.setdefault(q, []).append((line, (q, line, desc)))
    closure = idx.transitive_paths(direct, kinds=("call", "loop"))
    seen: Set[Tuple[str, int]] = set()
    for q in sorted(idx.functions):
        root = idx.functions[q]
        if not root.is_async:
            continue
        for key, path in sorted(closure.get(q, {}).items()):
            site_q, site_line, desc = key
            site_fn = idx.functions[site_q]
            if site_fn.is_async:
                continue  # inline in an async body: R1 reports it
            if (site_q, site_line) in seen:
                continue
            seen.add((site_q, site_line))
            if site_fn.ctx.allowed(site_line, "R10", "async-transitive"):
                continue
            chain = " -> ".join(
                f"{idx.functions[s].cls + '.' if idx.functions[s].cls else ''}"
                f"{idx.functions[s].name}" for s, _ in path)
            yield Finding(
                "R10", "async-transitive", site_fn.ctx.relpath, site_line,
                f"{desc} inside '{site_fn.name}' is reachable from "
                f"'async def {root.name}' ({root.ctx.relpath}) via "
                f"{chain} — it blocks the event loop; resolve off-loop or "
                f"justify with '# raylint: allow(async-transitive) <why>'")


# --------------------------------------------------------------------------
# R11: global static lock-order graph (whole-program closure of R2)

@project_rule("R11", "lock-order-graph")
def check_lock_order_graph(ctxs: List[FileContext],
                           engine) -> Iterator[Finding]:
    """R2 sees lock nestings written in one function; this collects lock
    acquisitions *across* function boundaries into one global order graph:
    holding A while calling ``f()`` orders A before every lock ``f`` may
    acquire transitively.  Cycles are potential deadlocks; each is
    reported once, anchored at an interprocedural edge's call site, with
    the full call path and in lockwatch's runtime cycle format (same
    ``sites`` identity), so a static finding and a lockwatch runtime
    report of the same inversion correlate.  Cycles whose every edge is a
    single-function nesting in ONE file are R2's findings and are not
    re-reported; cross-file direct nestings stay here, because R2's
    syntactic lock identity cannot merge ``LOCK`` with ``othermod.LOCK``."""
    from ray_tpu.devtools import lockwatch
    idx = engine.index(ctxs)
    direct: Dict[str, List[Tuple[int, str]]] = {}
    for q, fn in idx.functions.items():
        if fn.synthetic:
            continue              # arm statements belong to the dispatcher
        for lid, line, _held in fn.acquires:
            direct.setdefault(q, []).append((line, lid))
    closure = idx.transitive_paths(direct, kinds=("call",))
    # edge (a, b): a held while b acquired; witness = (fn, line, path, inter)
    edges: Dict[Tuple[str, str], Tuple[object, int, List[Tuple[str, int]],
                                       bool]] = {}
    for q in sorted(idx.functions):
        fn = idx.functions[q]
        if fn.synthetic:
            continue
        for lid, line, held in fn.acquires:
            for h in held:
                if h != lid:
                    edges.setdefault((h, lid), (fn, line, [(q, line)], False))
        for site in fn.call_sites:
            if site.kind != "call" or not site.locks_held or \
                    site.target not in idx.functions:
                continue
            for lid, path in closure.get(site.target, {}).items():
                for h in site.locks_held:
                    if h != lid:
                        edges.setdefault(
                            (h, lid),
                            (fn, site.line, [(q, site.line)] + path, True))
    succ: Dict[str, List[str]] = {}
    for a, b in edges:
        succ.setdefault(a, []).append(b)
    for comp in lockwatch._sccs(sorted(succ), succ):
        if len(comp) < 2:
            continue
        in_comp = set(comp)
        comp_edges = [(k, v) for k, v in sorted(edges.items())
                      if k[0] in in_comp and k[1] in in_comp]
        inter = [(k, v) for k, v in comp_edges if v[3]]
        files = {v[0].ctx.relpath for _, v in comp_edges}
        if not inter and len(files) < 2:
            continue  # single-file intra-function nesting: R2's domain
        anchor = None
        for key, (fn, line, path, _i) in (inter or comp_edges):
            if not fn.ctx.allowed(line, "R11", "lock-order-graph"):
                anchor = (key, fn, line, path)
                break
        if anchor is None:
            continue  # every interprocedural edge carries a justification
        (a, b), fn, line, path = anchor
        steps = " -> ".join(
            f"{idx.functions[s].name}@{idx.functions[s].ctx.relpath}:{ln}"
            for s, ln in path)
        others = "; ".join(
            f"{x} -> {y} at {v[0].ctx.relpath}:{v[1]}"
            for (x, y), v in comp_edges if (x, y) != (a, b))
        yield Finding(
            "R11", "lock-order-graph", fn.ctx.relpath, line,
            f"static {lockwatch.format_cycle('site-order', sorted(comp))}; "
            f"edge {a} -> {b} via {steps}"
            + (f"; conflicting edges: {others}" if others else "")
            + " (potential deadlock — same cycle identity as a lockwatch "
              "runtime report over these sites)")


# --------------------------------------------------------------------------
# R12: SPMD collective divergence (rank-dependent control flow)

_RANKISH = re.compile(
    r"(^|[._])(rank|world_rank|local_rank|node_rank|global_rank|world_size|"
    r"process_index|process_count|num_hosts|host_id|is_head|is_master|"
    r"is_chief|is_coordinator)($|[._(])", re.IGNORECASE)

_EXIT_CALLS = {"sys.exit", "os._exit", "exit", "quit", "os.abort"}


def _rank_dependent(test: ast.AST) -> Optional[str]:
    """The rank-ish name that makes *test* SPMD-divergent, or None."""
    for node in ast.walk(test):
        dn = _dotted(node) if isinstance(node, (ast.Name, ast.Attribute)) \
            else None
        if dn and _RANKISH.search(dn):
            return dn
        if isinstance(node, ast.Call):
            dn = _dotted(node.func)
            if dn and _RANKISH.search(dn):
                return dn + "()"
    return None


def _arm_exits(stmts: List[ast.stmt]) -> bool:
    """True if the statement list can leave the function (return/raise/
    sys.exit) — execution past the enclosing If then differs by rank."""
    for stmt in stmts:
        for node in _walk_pruned(stmt):
            if isinstance(node, (ast.Return, ast.Raise)):
                return True
            if isinstance(node, ast.Call) and \
                    _dotted(node.func) in _EXIT_CALLS:
                return True
    return False


@project_rule("R12", "collective-divergence")
def check_collective_divergence(ctxs: List[FileContext],
                                engine) -> Iterator[Finding]:
    """Every rank must execute the same collective sequence (podracer /
    pjit-at-scale SPMD contract): a collective, barrier, or
    checkpoint-commit call — direct or through the call graph — that only
    *some* ranks reach deadlocks the others.  Flagged shapes: a collective
    under a branch on rank/world-size state that the other arm does not
    match; a collective after a rank-dependent early exit; a collective
    inside a rank-dependent loop; a collective inside an ``except``
    handler (locally-divergent exception state — one rank's fault must
    not desync the collective schedule); and a ``CollectiveConfig``
    built from rank-dependent state — the (compression scheme, block
    size) pair folds into every rank's rendezvous fingerprint, so a
    per-rank config raises CollectiveDivergenceError at the group's
    first op rather than corrupting a half-quantized reduction.
    Uniform-by-construction shapes are justified with
    ``# raylint: allow(collective-divergence) <why>``."""
    idx = engine.index(ctxs)
    direct: Dict[str, List[Tuple[int, str]]] = {}
    for q, fn in idx.functions.items():
        for line, name in fn.collectives:
            direct.setdefault(q, []).append((line, name))
    closure = idx.transitive_paths(direct, kinds=("call",))

    def site_for(fn, node):
        return fn.site_by_node.get(id(node))

    def collectives_in(fn, stmts) -> Dict[str, Tuple[int, str]]:
        """name -> (line, via) for collectives in *stmts*, direct or
        through resolved calls (one witness each)."""
        out: Dict[str, Tuple[int, str]] = {}
        for stmt in stmts:
            for node in _walk_pruned(stmt):
                if not isinstance(node, ast.Call):
                    continue
                dn = _dotted(node.func) or ""
                last = dn.rsplit(".", 1)[-1]
                site = site_for(fn, node)
                if last in _cg.COLLECTIVE_NAMES or (
                        site and site.target in _cg.BARRIER_QNAMES):
                    out.setdefault(last, (node.lineno, dn))
                elif site and site.target in closure:
                    for name, path in closure[site.target].items():
                        chain = " -> ".join(
                            idx.functions[s].name for s, _ in path)
                        out.setdefault(name,
                                       (node.lineno, f"{dn} -> {chain}"))
        return out

    findings: Dict[Tuple[str, int, str], Finding] = {}

    def flag(fn, line, name, via, why):
        if fn.ctx.allowed(line, "R12", "collective-divergence"):
            return
        key = (fn.ctx.relpath, line, name)
        if key in findings:
            return
        findings[key] = Finding(
            "R12", "collective-divergence", fn.ctx.relpath, line,
            f"collective '{name}'"
            + (f" (via {via})" if via and via != name else "")
            + f" {why} — ranks that skip it deadlock the ones that don't; "
            f"make the schedule rank-uniform or justify with "
            f"'# raylint: allow(collective-divergence) <why>'")

    def walk_stmts(fn, stmts, div: Optional[str]):
        for stmt in stmts:
            if div is not None:
                for name, (line, via) in sorted(
                        collectives_in(fn, [stmt]).items()):
                    flag(fn, line, name, via, div)
            if isinstance(stmt, ast.If):
                dep = _rank_dependent(stmt.test)
                if dep and div is None:
                    body_cols = collectives_in(fn, stmt.body)
                    else_cols = collectives_in(fn, stmt.orelse)
                    for name, (line, via) in sorted(body_cols.items()):
                        if name not in else_cols:
                            flag(fn, line, name, via,
                                 f"is dominated by a branch on '{dep}' "
                                 f"(line {stmt.lineno}) with no matching "
                                 f"call on the other path")
                    for name, (line, via) in sorted(else_cols.items()):
                        if name not in body_cols:
                            flag(fn, line, name, via,
                                 f"is dominated by a branch on '{dep}' "
                                 f"(line {stmt.lineno}) with no matching "
                                 f"call on the other path")
                    # arms still get walked (except handlers, nested
                    # rank branches); duplicate sites dedup by key
                    walk_stmts(fn, stmt.body, div)
                    walk_stmts(fn, stmt.orelse, div)
                    body_exit = _arm_exits(stmt.body)
                    else_exit = _arm_exits(stmt.orelse) if stmt.orelse \
                        else False
                    if body_exit != else_exit:
                        div = (f"follows a rank-dependent early exit "
                               f"(branch on '{dep}' at line {stmt.lineno})")
                else:
                    walk_stmts(fn, stmt.body, div)
                    walk_stmts(fn, stmt.orelse, div)
            elif isinstance(stmt, (ast.While, ast.For)):
                cond = stmt.test if isinstance(stmt, ast.While) else stmt.iter
                dep = _rank_dependent(cond)
                loop_div = div
                if dep and div is None:
                    loop_div = (f"sits in a loop whose trip count depends "
                                f"on '{dep}' (line {stmt.lineno})")
                    for name, (line, via) in sorted(
                            collectives_in(fn, stmt.body).items()):
                        flag(fn, line, name, via, loop_div)
                else:
                    walk_stmts(fn, stmt.body, loop_div)
                walk_stmts(fn, stmt.orelse, div)
            elif isinstance(stmt, ast.Try):
                walk_stmts(fn, stmt.body, div)
                for handler in stmt.handlers:
                    hdiv = div or ("sits in an 'except' handler — entered "
                                   "only on the rank that hit the fault")
                    for name, (line, via) in sorted(
                            collectives_in(fn, handler.body).items()):
                        flag(fn, line, name, via, hdiv)
                walk_stmts(fn, stmt.orelse, div)
                walk_stmts(fn, stmt.finalbody, div)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                walk_stmts(fn, stmt.body, div)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # separate FunctionInfo / scope
        return

    def flag_config(fn, node, dep):
        if fn.ctx.allowed(node.lineno, "R12", "collective-divergence"):
            return
        key = (fn.ctx.relpath, node.lineno, "CollectiveConfig")
        if key in findings:
            return
        findings[key] = Finding(
            "R12", "collective-divergence", fn.ctx.relpath, node.lineno,
            f"CollectiveConfig built from rank-dependent state ('{dep}') "
            f"— the (compression scheme, block size) pair folds into "
            f"every rank's rendezvous fingerprint, so per-rank configs "
            f"raise CollectiveDivergenceError at the group's first op; "
            f"build ONE config for the whole group or justify with "
            f"'# raylint: allow(collective-divergence) <why>'")

    for q in sorted(idx.functions):
        fn = idx.functions[q]
        if fn.synthetic:
            continue              # arm statements belong to the dispatcher
        walk_stmts(fn, list(fn.node.body), None)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            dn = _dotted(node.func) or ""
            if dn.rsplit(".", 1)[-1] != "CollectiveConfig":
                continue
            for sub in list(node.args) + [kw.value for kw in node.keywords]:
                dep = _rank_dependent(sub)
                if dep:
                    flag_config(fn, node, dep)
                    break
    for key in sorted(findings):
        yield findings[key]


# --------------------------------------------------------------------------
# R13: config-knob and chaos-point drift (declared vs. used closure)

_CONFIG_METHODS = {"get", "set", "define", "apply_system_config", "to_dict",
                   "keys", "items", "values", "setdefault", "snapshot",
                   "reset"}
_CHAOS_SPEC_RE = re.compile(
    r"^(?:\d+\s*:)?\s*([a-z_][a-z0-9_]*(?:\.[a-z0-9_]+)+)\s*"
    r"(?:\[[^\]]*\])*\s*(?:@[\w%+.]+)?\s*=\s*(?:delay|drop|reset|error|exit)")


def _is_test_path(relpath: str) -> bool:
    norm = relpath.replace("\\", "/")
    return norm.startswith("tests/") or \
        os.path.basename(norm).startswith("test_")


def _config_receiver(name: str, ctx: FileContext) -> bool:
    """Is local name *name* bound to the global ``_config`` registry?

    True only when the file imported it from ``ray_tpu._private.config``
    (any alias) or *is* that module — bare ``cfg`` locals elsewhere are
    plain dicts/dataclasses, not the knob registry."""
    origin = ctx.import_origin.get(name, "")
    if origin == "ray_tpu._private.config._config" or \
            origin == "ray_tpu._private.config":
        return True
    return name == "_config" and \
        ctx.relpath.replace("\\", "/").endswith("_private/config.py")


def _chaos_inject_point(node: ast.Call, ctx: FileContext) -> Optional[str]:
    """Constant point name if *node* is a ``chaos.inject("...")`` call."""
    dn = _dotted(node.func)
    is_inject = False
    if dn is not None and dn.split(".")[-1] == "inject":
        head = dn.split(".")[0]
        origin = ctx.import_origin.get(head, "")
        is_inject = ("chaos" in dn or "chaos" in origin or
                     ctx.from_imports.get("inject", "").startswith(
                         "ray_tpu.chaos"))
    if is_inject and node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


@project_rule("R13", "config-drift")
def check_config_drift(ctxs: List[FileContext], _engine) -> Iterator[Finding]:
    """Two declared-vs-used closures that otherwise drift silently.

    **Config knobs**: every ``_config.define("name", ...)`` must be read
    somewhere (``_config.get("name")`` or ``_config.name``) — a dead knob
    is a promise the runtime no longer keeps — and every read/set must
    name a defined knob (an undefined name fails at runtime, but only on
    the path that reads it).  **Chaos points**: every
    ``chaos.inject("point")`` site in the runtime must be exercised by at
    least one test (a spec string or direct inject in ``tests/``), else
    the fault path is dead weight chaos never validates; and every
    dotted point a test spec references must exist in the runtime (or be
    injected by the test itself), else the test silently runs fault-free."""
    defines: Dict[str, Tuple[FileContext, int]] = {}
    reads: Set[str] = set()
    uses: List[Tuple[str, FileContext, int]] = []   # get/set/attr sites
    dynamic_access = False
    declared_points: Dict[str, Tuple[FileContext, int]] = {}
    test_points: Set[str] = set()
    test_injects: Set[str] = set()
    spec_refs: List[Tuple[str, FileContext, int]] = []
    have_tests = any(_is_test_path(c.relpath) for c in ctxs)

    for ctx in ctxs:
        is_test = _is_test_path(ctx.relpath)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                point = _chaos_inject_point(node, ctx)
                if point is not None:
                    if is_test:
                        test_injects.add(point)
                        test_points.add(point)
                    else:
                        declared_points.setdefault(point,
                                                   (ctx, node.lineno))
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        _config_receiver(node.func.value.id, ctx):
                    attr = node.func.attr
                    if attr in ("get", "set", "define") and node.args:
                        arg = node.args[0]
                        if isinstance(arg, ast.Constant) and \
                                isinstance(arg.value, str):
                            if attr == "define":
                                defines.setdefault(arg.value,
                                                   (ctx, node.lineno))
                            else:
                                if attr == "get":
                                    reads.add(arg.value)
                                uses.append((arg.value, ctx, node.lineno))
                        else:
                            dynamic_access = True
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    _config_receiver(node.value.id, ctx) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.attr not in _CONFIG_METHODS and \
                    not node.attr.startswith("_"):
                reads.add(node.attr)
                uses.append((node.attr, ctx, node.lineno))
            elif isinstance(node, ast.Constant) and is_test and \
                    isinstance(node.value, str) and "=" in node.value:
                for seg in node.value.split(";"):
                    m = _CHAOS_SPEC_RE.match(seg.strip())
                    if m:
                        test_points.add(m.group(1))
                        spec_refs.append((m.group(1), ctx,
                                          getattr(node, "lineno", 1)))

    if defines:
        if not dynamic_access:
            for name in sorted(defines):
                ctx, line = defines[name]
                if name in reads or ctx.allowed(line, "R13", "config-drift"):
                    continue
                yield Finding(
                    "R13", "config-drift", ctx.relpath, line,
                    f"config knob '{name}' is defined but never read "
                    f"anywhere in the tree — dead knob or missing wiring; "
                    f"wire it in, delete it, or justify with "
                    f"'# raylint: allow(config-drift) <why>'")
        for name, ctx, line in sorted(uses, key=lambda u: (u[1].relpath,
                                                           u[2], u[0])):
            if name in defines or ctx.allowed(line, "R13", "config-drift"):
                continue
            yield Finding(
                "R13", "config-drift", ctx.relpath, line,
                f"config knob '{name}' is accessed here but never defined "
                f"— this raises at runtime, but only on the path that "
                f"reads it")

    if have_tests and declared_points:
        for point in sorted(declared_points):
            ctx, line = declared_points[point]
            if point in test_points or \
                    ctx.allowed(line, "R13", "config-drift"):
                continue
            yield Finding(
                "R13", "config-drift", ctx.relpath, line,
                f"chaos point '{point}' is declared here but never "
                f"exercised by tests/ — the fault path is unvalidated; "
                f"add a chaos test or justify with "
                f"'# raylint: allow(config-drift) <why>'")
        for point, ctx, line in sorted(spec_refs,
                                       key=lambda r: (r[1].relpath, r[2])):
            if point in declared_points or point in test_injects or \
                    ctx.allowed(line, "R13", "config-drift"):
                continue
            yield Finding(
                "R13", "config-drift", ctx.relpath, line,
                f"test chaos spec references injection point '{point}' "
                f"which no runtime inject() declares — the test runs "
                f"fault-free")


# --------------------------------------------------------------------------
# R14: observability spans must be context-managed (closed on every path)

_OBS_MODULE = "ray_tpu.observability"


def _is_obs_span_call(node: ast.Call, ctx: FileContext) -> bool:
    """True when *node* calls ``ray_tpu.observability``'s ``span``."""
    dn = _dotted(node.func)
    if dn is None:
        return False
    if dn == "span":
        origin = ctx.import_origin.get("span", "")
        return origin == _OBS_MODULE + ".span" or \
            ctx.from_imports.get("span", "") == _OBS_MODULE
    if not dn.endswith(".span"):
        return False
    head = dn.split(".")[0]
    origin = ctx.import_origin.get(head, "")
    return origin == _OBS_MODULE or \
        origin + "." + dn.split(".", 1)[1] == _OBS_MODULE + ".span" or \
        dn == _OBS_MODULE + ".span"


@rule("R14", "span-leak")
def check_span_leak(ctx: FileContext) -> Iterator[Finding]:
    """``observability.span(...)`` is context-manager-only outside the
    observability package: constructed bare (bound to a name, passed
    around, or ``__enter__``-ed by hand) there is an exit path — an
    exception between enter and exit — on which the span never records
    and its context var never resets, silently re-parenting every later
    span in that thread.  ``with observability.span(...):`` closes both
    on every path."""
    norm = ctx.relpath.replace("\\", "/")
    if "observability" in norm.split("/")[:-1]:
        return  # the package itself implements the context manager
    with_calls: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    with_calls.add(id(item.context_expr))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or id(node) in with_calls:
            continue
        if not _is_obs_span_call(node, ctx):
            continue
        if ctx.allowed(node.lineno, "R14", "span-leak"):
            continue
        yield Finding(
            "R14", "span-leak", ctx.relpath, node.lineno,
            "observability.span(...) outside a 'with' statement: the span "
            "is not closed on every exit path (leaked context var, span "
            "never recorded) — use 'with observability.span(...):', or "
            "justify with '# raylint: allow(span-leak) <why>'")


# R15: metric label cardinality (unbounded tag values)

_METRIC_METHODS = {"inc", "set", "observe", "set_default_tags"}
_UNBOUNDED_ID_RE = re.compile(
    r"(?:^|_)(?:task_id|object_id|actor_id|trace_id|span_id|request_id|"
    r"job_id|node_id|oid|uuid|addr|address|peer)$", re.IGNORECASE)


def _unbounded_tag_value(expr: ast.expr) -> bool:
    """True when a tag-value expression smells like per-entity runtime
    data (an id hex, a raw address, an f-string embedding one) rather
    than a small closed set of label values."""
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Attribute) and fn.attr == "hex":
            return True
        if isinstance(fn, ast.Name) and fn.id in ("str", "repr") and \
                expr.args:
            return _unbounded_tag_value(expr.args[0])
        return False
    if isinstance(expr, ast.Name):
        return bool(_UNBOUNDED_ID_RE.search(expr.id))
    if isinstance(expr, ast.Attribute):
        return bool(_UNBOUNDED_ID_RE.search(expr.attr))
    if isinstance(expr, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue)
                   and _unbounded_tag_value(v.value)
                   for v in expr.values)
    if isinstance(expr, ast.BinOp):
        return _unbounded_tag_value(expr.left) or \
            _unbounded_tag_value(expr.right)
    if isinstance(expr, ast.Subscript):
        return _unbounded_tag_value(expr.value)
    return False


@rule("R15", "metrics-cardinality")
def check_metrics_cardinality(ctx: FileContext) -> Iterator[Finding]:
    """A metric tag whose value is per-entity runtime data (object/task/
    trace ids, raw peer addresses) mints a new time series per entity:
    the registry, every scrape and the federated export all grow without
    bound, and the aggregation the label was supposed to enable drowns
    in one-sample series.  Flags ``inc``/``set``/``observe``/
    ``set_default_tags`` calls whose ``tags`` dict-literal values look
    unbounded (``.hex()`` of an id, id-ish names, f-strings embedding
    either).  Values genuinely bounded by something small (cluster
    size) are justified in place with
    ``# raylint: allow(metrics-cardinality) <why>``."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute) or \
                node.func.attr not in _METRIC_METHODS:
            continue
        tags = None
        for kw in node.keywords:
            if kw.arg == "tags":
                tags = kw.value
        if tags is None and node.func.attr == "set_default_tags" and \
                node.args:
            tags = node.args[0]
        if not isinstance(tags, ast.Dict):
            continue
        bad = [k.value for k, v in zip(tags.keys, tags.values)
               if isinstance(k, ast.Constant) and _unbounded_tag_value(v)]
        if not bad:
            continue
        if ctx.allowed(node.lineno, "R15", "metrics-cardinality"):
            continue
        yield Finding(
            "R15", "metrics-cardinality", ctx.relpath, node.lineno,
            f"metric tag(s) {', '.join(repr(b) for b in bad)} take "
            "per-entity runtime values (ids / raw addresses): every "
            "entity mints a new time series, growing the registry and "
            "every scrape without bound — tag with a bounded category "
            "instead, or justify with "
            "'# raylint: allow(metrics-cardinality) <why>'")


# --------------------------------------------------------------------------
# R16: resource lifecycle — acquire/release on every path (dataflow layer)

@project_rule("R16", "resource-leak")
def check_resource_leak(ctxs: List[FileContext],
                        engine) -> Iterator[Finding]:
    """An OS-backed resource (socket, file handle, mmap, non-daemon
    thread, executor pool) acquired on some path but neither released
    nor ownership-transferred before the function exits on that path.
    The path-sensitive walk in :mod:`ray_tpu.devtools.dataflow` models
    explicit control flow — ``return``/``raise``, ``try``/``except``/
    ``finally`` exception edges, and constructor aborts inside
    ``__init__`` — and treats anything it cannot prove it understands
    (stores, container adds, resolved callees that keep their argument,
    captures) as a transfer, so it under-reports rather than guesses.
    Dynamic handoffs the walker cannot see are asserted in place with
    ``# raylint: transfer(<kind>) <why>`` on the acquire line; wrong-rule
    findings use ``# raylint: allow(resource-leak) <why>``."""
    idx = engine.index(ctxs)
    for q in sorted(idx.functions):
        fn = idx.functions[q]
        if fn.synthetic:
            continue              # arm statements belong to the dispatcher
        for fact, exit_state in _df.resource_leaks(fn, idx):
            if fn.ctx.allowed(fact.line, "R16", "resource-leak"):
                continue
            where = {"return": "the return at line %d" % exit_state.line,
                     "fall": "the fall-through exit at line %d"
                             % exit_state.line,
                     "raise": "the raise at line %d" % exit_state.line,
                     "ctor-raise": "__init__ aborting if line %d raises"
                                   % exit_state.line}[exit_state.kind]
            steps = " -> ".join(
                f"{note}@{ln}" for ln, note in exit_state.trail[-6:])
            yield Finding(
                "R16", "resource-leak", fn.ctx.relpath, fact.line,
                f"{fact.kind} '{fact.var or '<anon>'}' acquired here in "
                f"'{fn.name}' is still open at {where}"
                + (f" (path: {steps})" if steps else "")
                + " — release it on every path, hand it to an owner, or "
                  "mark the handoff with '# raylint: transfer("
                + fact.kind + ") <why>'")


# --------------------------------------------------------------------------
# R17: deadline propagation — no naked blocking under a time budget

@project_rule("R17", "deadline-drop")
def check_deadline_drop(ctxs: List[FileContext],
                        engine) -> Iterator[Finding]:
    """A blocking primitive with no timeout (``.wait()`` / zero-arg
    ``.join()`` / ``.result()`` / lock ``.acquire()`` / queue ``.get()``
    / ``concurrent.futures.wait``) reachable over ``call`` edges from a
    deadline-scoped entry point — a function that takes a ``deadline``/
    ``timeout``/``budget`` parameter or arms a ``BackoffPolicy``
    deadline.  Such a call silently drops the budget the caller was
    promised: the drain orchestrator, checkpoint engine and RPC layer
    all size their budgets assuming callees stay bounded.  Pass the
    remaining budget down (``timeout=deadline - time.monotonic()``), or
    justify with ``# raylint: allow(deadline-drop) <why>``."""
    idx = engine.index(ctxs)
    direct: Dict[str, List[Tuple[int, Tuple[str, int, str]]]] = {}
    for q, fn in idx.functions.items():
        if fn.is_async or fn.synthetic:
            continue              # event-loop blocking is R1/R10's domain
        for line, desc in _df.naked_blocking(fn.node, fn.ctx):
            direct.setdefault(q, []).append((line, (q, line, desc)))
    closure = idx.transitive_paths(direct, kinds=("call",))
    seen: Set[Tuple[str, int]] = set()
    for q in sorted(idx.functions):
        root = idx.functions[q]
        if root.is_async or root.synthetic:
            continue
        params = _df.deadline_params(root.node)
        scope = (f"'{root.name}({', '.join(params)})'" if params else None)
        if scope is None:
            line = _df.arms_backoff_budget(root.node)
            if line is None:
                continue
            scope = f"'{root.name}' (BackoffPolicy deadline at line {line})"
        for key, path in sorted(closure.get(q, {}).items()):
            site_q, site_line, desc = key
            site_fn = idx.functions[site_q]
            if site_fn.is_async or (site_q, site_line) in seen:
                continue
            seen.add((site_q, site_line))
            if site_fn.ctx.allowed(site_line, "R17", "deadline-drop"):
                continue
            chain = " -> ".join(
                f"{idx.functions[s].name}@{ln}" for s, ln in path)
            yield Finding(
                "R17", "deadline-drop", site_fn.ctx.relpath, site_line,
                f"{desc} blocks with no bound under the deadline scope "
                f"{scope} (witness: {chain}) — pass the remaining budget "
                "down, or justify with "
                "'# raylint: allow(deadline-drop) <why>'")


# --------------------------------------------------------------------------
# R18: protocol conformance — senders, handlers, replies, lifecycle

@project_rule("R18", "protocol")
def check_protocol_conformance(ctxs: List[FileContext],
                               engine) -> Iterator[Finding]:
    """Cross-checks the RPC message vocabulary and the PR 8 node
    lifecycle, in four parts: (a) every ``pb.<METHOD>`` handed to a send
    primitive must have a dispatch arm somewhere (python ``.method ==``
    comparisons or a native ``case raytpu::M:``); (b) every
    python-side dispatch arm must have a sender somewhere (python or a
    native ``set_method``); (c) a handler that replies through its
    ``RpcContext`` must reply exactly once on every non-raising path it
    completes (the conn loop error-replies for raising paths); (d) every
    static ``<node>.state = "<STATE>"`` write must be a transition the
    declared ``dataflow.NODE_LIFECYCLE`` table admits.  Unknowns (a
    context that escapes, an unguarded write to a reachable state)
    degrade to silence, never to a guessed finding."""
    idx = engine.index(ctxs)
    base = ""
    for ctx in ctxs:
        rel = ctx.relpath.replace("\\", "/")
        if rel.startswith("ray_tpu/") or "/ray_tpu/" in rel:
            base = ctx.path[:-len(ctx.relpath)] if \
                ctx.path.endswith(ctx.relpath) else \
                ctx.path[:ctx.path.rfind(rel.split("/", 1)[0])]
            break
    native_handled, native_sent = _df.native_protocol_facts(
        os.path.join(base, "ray_tpu", "_native")) if base else (set(), set())
    proto_names = _df.proto_method_names(
        os.path.join(base, "ray_tpu", "protocol", "raytpu.proto")) \
        if base else set()

    sends = _df.protocol_sends(ctxs)
    handlers = _df.protocol_handlers(ctxs)
    sent_names = {m for m, _c, _l in sends} | native_sent
    handled_names = {m for m, _c, _l in handlers} | native_handled
    skip = {"METHOD_UNSPECIFIED"}
    if proto_names:
        # names outside the Method enum (other pb constants riding the
        # same attribute shape) are not protocol methods at all
        universe = proto_names - skip
    else:
        universe = (sent_names | handled_names) - skip

    reported: Set[Tuple[str, str, int]] = set()
    for m, ctx, line in sorted(sends, key=lambda s: (s[1].relpath, s[2])):
        if m not in universe or m in handled_names:
            continue
        if (m, ctx.relpath, line) in reported:
            continue
        reported.add((m, ctx.relpath, line))
        if ctx.allowed(line, "R18", "protocol"):
            continue
        yield Finding(
            "R18", "protocol", ctx.relpath, line,
            f"message kind {m} is sent here but no dispatcher handles it "
            "(checked python '.method ==' arms and the native "
            "'case raytpu::' switch) — the peer will error-reply every "
            "call; add the handler or retire the sender")
    seen_handler: Set[str] = set()
    for m, ctx, line in sorted(handlers,
                               key=lambda s: (s[1].relpath, s[2])):
        if m not in universe or m in sent_names or m in seen_handler:
            continue
        seen_handler.add(m)
        if ctx.allowed(line, "R18", "protocol"):
            continue
        yield Finding(
            "R18", "protocol", ctx.relpath, line,
            f"dispatch arm for {m} has no sender anywhere (python send "
            "primitives and native set_method checked) — dead protocol "
            "surface; retire the arm or wire up the caller")

    for q in sorted(idx.functions):
        fn = idx.functions[q]
        if fn.is_async or fn.synthetic:
            continue
        recv = _df.reply_candidates(fn)
        if recv is None:
            continue
        flow = _df.FunctionDataflow(fn.node, fn.ctx, reply_recv=recv)
        if flow.is_generator:
            continue
        exits = flow.run()
        if flow.reply_recv_escaped:
            continue              # a helper we can't see may reply
        for ex in exits:
            if ex.kind in ("raise", "ctor-raise"):
                if ex.replies <= 1:
                    continue      # conn loop error-replies raising paths
            if ex.replies == 1:
                continue
            line = ex.line if ex.replies else fn.node.lineno
            if fn.ctx.allowed(line, "R18", "protocol"):
                continue
            steps = " -> ".join(f"{note}@{ln}" for ln, note in ex.trail[-6:])
            what = ("never replies" if ex.replies == 0
                    else f"replies {ex.replies} times")
            yield Finding(
                "R18", "protocol", fn.ctx.relpath, line,
                f"handler '{fn.name}' {what} on the path exiting at line "
                f"{ex.line}" + (f" (path: {steps})" if steps else "")
                + f" — every completed path must call {recv}.reply/"
                  f"{recv}.reply_error exactly once")
            break                 # one witness path per handler

    legal_targets = {t for _f, t in _df.NODE_LIFECYCLE["transitions"]}
    for ctx, line, recv, froms, to, guard_line in \
            _df.lifecycle_writes(ctxs):
        if ctx.allowed(line, "R18", "protocol"):
            continue
        if froms == {"*"}:
            if to in legal_targets:
                continue
            yield Finding(
                "R18", "protocol", ctx.relpath, line,
                f"node-lifecycle write '{recv}.state = \"{to}\"' targets "
                "a state no declared transition reaches "
                "(dataflow.NODE_LIFECYCLE) — fix the write or extend the "
                "declared machine")
            continue
        bad = sorted(f for f in froms
                     if (f, to) not in _df.NODE_LIFECYCLE["transitions"])
        if bad:
            yield Finding(
                "R18", "protocol", ctx.relpath, line,
                f"undeclared node-lifecycle transition "
                f"{' / '.join(repr(b) for b in bad)} -> {to!r} (guard at "
                f"line {guard_line}) — dataflow.NODE_LIFECYCLE is the "
                "declared machine; fix the transition or extend the table")


# --------------------------------------------------------------------------
# R19: distributed deadlock — blocking-wait cycles over the stitched graph

@project_rule("R19", "distributed-deadlock")
def check_distributed_deadlock(ctxs: List[FileContext],
                               engine) -> Iterator[Finding]:
    """Deadlocks that only exist once the process boundary is crossed,
    found on the cross-process edges the stitch pass adds (rpc ``kind``
    call sites into synthesized dispatch arms).  Two arms: (a) a
    *wait cycle* — handling method M can issue a synchronous RPC whose
    handler (transitively) issues a synchronous RPC back into M; with
    the request/reply slots saturated in both directions, two daemons
    wait on each other forever; (b) *lock held across RPC* — a thread
    holds lock L while blocking on a synchronous send of M, and M's
    handler can re-acquire the same lock node L: two symmetric daemons
    doing this to each other is AB/BA across the wire.  Both arms
    report in lockwatch's ``CYCLE (site-order)`` format over
    ``rpc:<METHOD>`` / lock sites, so a static finding and a runtime
    lockwatch report of the same shape correlate.  Fire-and-forget
    sends (``call_async``/``send_oneway``/``push``) never wait and are
    never part of a cycle here."""
    from ray_tpu.devtools import lockwatch
    idx = engine.index(ctxs)
    # facts: synchronous sends, keyed for the method-level closure
    direct: Dict[str, List[Tuple[int, Tuple[str, str, int]]]] = {}
    for q, line, m, sync, _held, _targets in idx.rpc_sites:
        if sync:
            direct.setdefault(q, []).append((line, (m, q, line)))
    closure = idx.transitive_paths(direct, kinds=("call",))

    # (a) method graph: rpc:M -> rpc:M2 when an arm handling M can reach
    # a synchronous send of M2 over ordinary call edges
    out_sends: Dict[str, List[Tuple[str, Tuple[str, int],
                                    List[Tuple[str, int]]]]] = {}
    succ: Dict[str, List[str]] = {}
    for m in sorted(idx.rpc_arms):
        node = f"rpc:{m}"
        outs: Set[str] = set()
        for aq in idx.rpc_arms[m]:
            for key, path in sorted(closure.get(aq, {}).items()):
                m2, sq, sline = key
                outs.add(f"rpc:{m2}")
                out_sends.setdefault(node, []).append(
                    (f"rpc:{m2}", (sq, sline), path))
        succ[node] = sorted(outs)
    for comp in lockwatch._sccs(sorted(succ), succ):
        if len(comp) < 2 and comp[0] not in succ.get(comp[0], ()):
            continue
        in_comp = set(comp)
        anchor = None
        for node in sorted(in_comp):
            for to, (sq, sline), path in sorted(out_sends.get(node, [])):
                if to not in in_comp:
                    continue
                site_fn = idx.functions[sq]
                if site_fn.ctx.allowed(sline, "R19", "distributed-deadlock"):
                    continue
                anchor = (node, to, site_fn, sline, path)
                break
            if anchor:
                break
        if anchor is None:
            continue              # every edge carries a justification
        node, to, site_fn, sline, path = anchor
        chain = " -> ".join(
            f"{idx.functions[s].name}@{ln}" for s, ln in path)
        yield Finding(
            "R19", "distributed-deadlock", site_fn.ctx.relpath, sline,
            f"static {lockwatch.format_cycle('site-order', sorted(in_comp))}"
            f"; handling {node[4:]} can synchronously send {to[4:]} here "
            f"(witness: {chain}) — with request slots saturated both ways "
            "the two daemons wait on each other forever; make one hop "
            "asynchronous or justify with "
            "'# raylint: allow(distributed-deadlock) <why>'")

    # (b) lock held across a synchronous send whose handler can
    # re-acquire the same lock node
    acq: Dict[str, List[Tuple[int, str]]] = {}
    for q, fn in idx.functions.items():
        for lid, line, _held in fn.acquires:
            acq.setdefault(q, []).append((line, lid))
    acq_closure = idx.transitive_paths(acq, kinds=("call",))
    seen: Set[Tuple[str, int, str, str]] = set()
    for q, line, m, sync, held, targets in sorted(idx.rpc_sites):
        if not sync or not held:
            continue
        fn = idx.functions[q]
        for aq in targets:
            reacquired = set(acq_closure.get(aq, {}))
            for lid in sorted(set(held) & reacquired):
                key = (fn.ctx.relpath, line, lid, m)
                if key in seen:
                    continue
                seen.add(key)
                if fn.ctx.allowed(line, "R19", "distributed-deadlock"):
                    continue
                lpath = acq_closure[aq][lid]
                chain = " -> ".join(
                    f"{idx.functions[s].name}@{ln}" for s, ln in lpath)
                yield Finding(
                    "R19", "distributed-deadlock", fn.ctx.relpath, line,
                    f"static "
                    f"{lockwatch.format_cycle('site-order', sorted([lid, 'rpc:' + m]))}"
                    f"; '{fn.name}' holds {lid} while synchronously "
                    f"sending {m}, and the {m} handler can re-acquire "
                    f"{lid} ({chain}) — two peers doing this to each "
                    "other is AB/BA across the wire (lockwatch reports "
                    "the same cycle at runtime under RAY_TPU_LOCKWATCH); "
                    "release the lock before the call or justify with "
                    "'# raylint: allow(distributed-deadlock) <why>'")


# --------------------------------------------------------------------------
# R20: handler stall — unbounded blocking reachable from an RPC handler

@project_rule("R20", "handler-stall")
def check_handler_stall(ctxs: List[FileContext],
                        engine) -> Iterator[Finding]:
    """R17's naked-blocking catalog (bare ``.wait()`` / ``.join()`` /
    ``.result()`` / lock ``.acquire()`` / queue ``.get()``), rooted not
    at deadline scopes but at RPC dispatch arms: a handler that blocks
    without a bound stalls a dispatch-pool thread — and with the pool
    saturated, frame dispatch for *every* caller of that server.  A
    witness function on the path that takes a ``deadline``/``timeout``
    parameter or arms a ``BackoffPolicy`` budget bounds the wait (and
    puts it in R17's jurisdiction), so those paths are suppressed
    here."""
    idx = engine.index(ctxs)
    direct: Dict[str, List[Tuple[int, Tuple[str, int, str]]]] = {}
    for q, fn in idx.functions.items():
        # synthetic arms keep their facts: a bare wait written lexically
        # inside a dispatch arm must anchor under that arm's qname
        if fn.is_async:
            continue
        for line, desc in _df.naked_blocking(fn.node, fn.ctx):
            direct.setdefault(q, []).append((line, (q, line, desc)))
    closure = idx.transitive_paths(direct, kinds=("call",))
    seen: Set[Tuple[str, int]] = set()
    for m in sorted(idx.rpc_arms):
        for aq in idx.rpc_arms[m]:
            for key, path in sorted(closure.get(aq, {}).items()):
                site_q, site_line, desc = key
                site_fn = idx.functions[site_q]
                if (site_fn.ctx.relpath, site_line) in seen:
                    continue
                seen.add((site_fn.ctx.relpath, site_line))
                if any(_df.deadline_params(idx.functions[s].node)
                       or _df.arms_backoff_budget(idx.functions[s].node)
                       is not None for s, _ln in path):
                    continue      # budget-scoped: bounded, and R17's job
                if site_fn.ctx.allowed(site_line, "R20", "handler-stall"):
                    continue
                chain = " -> ".join(
                    f"{idx.functions[s].name}@{ln}" for s, ln in path)
                yield Finding(
                    "R20", "handler-stall", site_fn.ctx.relpath, site_line,
                    f"{desc} blocks with no bound and is reachable from "
                    f"the {m} dispatch arm (witness: {chain}) — a stalled "
                    "handler pins a dispatch thread and, pool exhausted, "
                    "stalls every caller of this server; bound the wait "
                    "or justify with '# raylint: allow(handler-stall) "
                    "<why>'")


# --------------------------------------------------------------------------
# R21: jit stability — recompile hazards at jit/pjit/shard_map sites

_R21_CTORS = {"jit", "pjit", "shard_map"}
_R21_CACHED_DECOS = {"functools.lru_cache", "lru_cache",
                     "functools.cache", "cache"}


def _jit_ctor_name(node: ast.Call, ctx: FileContext) -> Optional[str]:
    """The ctor leaf ("jit"/"pjit"/"shard_map") when *node* constructs a
    compiled callable, else None.  Requires a jax-rooted dotted name or
    an import provably from jax, so a local helper named ``jit`` does
    not trip the rule."""
    dn = _dotted(node.func) or ""
    leaf = dn.rsplit(".", 1)[-1]
    if leaf not in _R21_CTORS:
        return None
    head = dn.split(".", 1)[0]
    origin = ctx.import_origin.get(head, "")
    # jax proper or a jax shim module (jax_compat re-exports shard_map)
    if dn.startswith("jax.") or "jax" in origin:
        return leaf
    return None


def _jit_argnum_positions(call: ast.Call, kwname: str) -> Tuple[int, ...]:
    """Literal int / tuple-of-int ``static_argnums=``/``donate_argnums=``
    positions on a jit construction, else () (dynamic specs are not
    audited)."""
    for kw in call.keywords:
        if kw.arg != kwname:
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return ()
                out.append(e.value)
            return tuple(out)
    return ()


def _jit_registry(ctx: FileContext) -> Dict[str, Tuple[Tuple[int, ...],
                                                       Tuple[int, ...], int]]:
    """Callable text -> (static_argnums, donate_argnums, def line) for
    jit-wrapped callables this file constructs and later calls by name:
    ``X = jax.jit(f, ...)`` assignments (Name or ``self.X`` targets) and
    ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` decorated defs."""
    reg: Dict[str, Tuple[Tuple[int, ...], Tuple[int, ...], int]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.value, ast.Call) and \
                _jit_ctor_name(node.value, ctx):
            tgt = _dotted(node.targets[0])
            if tgt:
                reg[tgt] = (_jit_argnum_positions(node.value,
                                                  "static_argnums"),
                            _jit_argnum_positions(node.value,
                                                  "donate_argnums"),
                            node.lineno)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dn = _dotted(target)
                if dn in _JIT_NAMES and isinstance(dec, ast.Call):
                    reg[node.name] = (
                        _jit_argnum_positions(dec, "static_argnums"),
                        _jit_argnum_positions(dec, "donate_argnums"),
                        node.lineno)
                elif dn in ("functools.partial", "partial") and \
                        isinstance(dec, ast.Call) and dec.args and \
                        _dotted(dec.args[0]) in _JIT_NAMES:
                    reg[node.name] = (
                        _jit_argnum_positions(dec, "static_argnums"),
                        _jit_argnum_positions(dec, "donate_argnums"),
                        node.lineno)
    return reg


def _r21_msg(what: str) -> str:
    return (what + " — every distinct trace recompiles ("
            "compile time is a first-order cost at scale); "
            "justify with '# raylint: allow(jit-stability) <why>'")


@rule("R21", "jit-stability")
def check_jit_stability(ctx: FileContext) -> Iterator[Finding]:
    """Recompile and stale-buffer hazards at ``jax.jit`` / ``pjit`` /
    ``shard_map`` sites: (a) constructing a compiled callable inside a
    loop, or (b) per call — built and invoked within one function
    without being stored on an object, returned to a caching caller, or
    memoized — throws away the compile cache every iteration/call; (c)
    a Python-scalar ``len(...)`` fed straight into a jitted call varies
    the trace with batch size unless the caller routes shapes through
    ``pad_items`` (the blessed pad-to-bucket allowlist); (d) a
    ``static_argnums`` position fed a list/dict/set (unhashable → a
    ``TypeError`` at call time) or a raw ``.shape`` (a new trace per
    shape); (e) a buffer passed at a ``donate_argnums`` position is
    dead after the call — reading it later without rebinding returns
    garbage or raises.  Dynamic constructs the checks cannot prove
    degrade to silence."""
    registry = _jit_registry(ctx)

    def flag(line: int, what: str) -> Optional[Finding]:
        if ctx.allowed(line, "R21", "jit-stability"):
            return None
        return Finding("R21", "jit-stability", ctx.relpath, line,
                       _r21_msg(what))

    seen: Set[Tuple[int, str]] = set()

    def emit(line: int, check: str, what: str) -> Iterator[Finding]:
        if (line, check) in seen:
            return
        seen.add((line, check))
        f = flag(line, what)
        if f:
            yield f

    # (a) jit construction inside a loop — any scope, module level too
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                leaf = _jit_ctor_name(sub, ctx)
                if leaf:
                    yield from emit(
                        sub.lineno, "loop",
                        f"'{leaf}' constructed inside a loop (line "
                        f"{node.lineno}): a fresh callable per iteration "
                        "never hits the compile cache; hoist it out")

    for cls, fn in _funcs_with_class(ctx.tree):
        cached = any(_dotted(d.func if isinstance(d, ast.Call) else d)
                     in _R21_CACHED_DECOS for d in fn.decorator_list)
        calls_pad = any(isinstance(n, ast.Call)
                        and (_dotted(n.func) or "").rsplit(".", 1)[-1]
                        == "pad_items" for n in _walk_pruned(fn))

        # (b) constructed and invoked per call of this function
        if fn.name != "__init__" and not cached:
            local_ctors: Dict[str, Tuple[int, str]] = {}
            returned: Set[str] = set()
            called: Set[str] = set()
            for n in _walk_pruned(fn):
                if isinstance(n, ast.Call):
                    inner = n.func
                    if isinstance(inner, ast.Call):
                        ileaf = _jit_ctor_name(inner, ctx)
                        if ileaf:
                            yield from emit(
                                inner.lineno, "per-call",
                                f"'{ileaf}(...)' built and invoked in one "
                                f"expression inside '{fn.name}': every "
                                "call re-traces; build once and reuse")
                    if isinstance(n.func, ast.Name):
                        called.add(n.func.id)
                elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and isinstance(n.value, ast.Call):
                    leaf = _jit_ctor_name(n.value, ctx)
                    if leaf:
                        local_ctors[n.targets[0].id] = (n.value.lineno, leaf)
                elif isinstance(n, ast.Return) and n.value is not None:
                    if isinstance(n.value, ast.Name):
                        returned.add(n.value.id)
            for name, (line, leaf) in sorted(local_ctors.items()):
                if name in called and name not in returned:
                    yield from emit(
                        line, "per-call",
                        f"'{leaf}' result bound to local '{name}' and "
                        f"called inside '{fn.name}': the compiled "
                        "callable dies with the frame, so every call "
                        "re-traces; cache it (module level, an "
                        "attribute, or functools.lru_cache)")

        # (c)-(e) at call sites of registry entries
        for n in _walk_pruned(fn):
            if not isinstance(n, ast.Call):
                continue
            tgt = _dotted(n.func)
            if tgt is None or tgt not in registry:
                continue
            static, donate, _dline = registry[tgt]
            if not calls_pad:
                for arg in n.args:
                    hit = next(
                        (s for s in ast.walk(arg)
                         if isinstance(s, ast.Call)
                         and _dotted(s.func) == "len"), None)
                    if hit is not None:
                        yield from emit(
                            n.lineno, "scalar",
                            f"Python scalar 'len(...)' flows into jitted "
                            f"'{tgt}': the trace re-specializes per batch "
                            "size; bucket shapes with pad_items first")
                        break
            for pos in static:
                if pos >= len(n.args):
                    continue
                arg = n.args[pos]
                if isinstance(arg, (ast.List, ast.Dict, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp)):
                    yield from emit(
                        n.lineno, "static",
                        f"static_argnums position {pos} of '{tgt}' is fed "
                        "an unhashable literal: jit static args must hash "
                        "(TypeError at call time); pass a tuple or mark "
                        "the arg dynamic")
                elif isinstance(arg, ast.Attribute) and arg.attr == "shape":
                    yield from emit(
                        n.lineno, "static",
                        f"static_argnums position {pos} of '{tgt}' is fed "
                        "a raw '.shape': a new trace per shape defeats "
                        "the cache; bucket the shape or pass it dynamic")
            for pos in donate:
                if pos >= len(n.args):
                    continue
                dtxt = _dotted(n.args[pos])
                if dtxt is None:
                    continue
                # the assignment consuming the call's result is the
                # canonical rebind (`params = update(params, ...)`), so
                # Stores count from the call line itself; Loads only
                # after the call expression ends
                call_end = getattr(n, "end_lineno", n.lineno)
                rebound = False
                used_line = None
                for m in _walk_pruned(fn):
                    mline = getattr(m, "lineno", 0)
                    if mline < n.lineno or \
                            not isinstance(m, (ast.Name, ast.Attribute)) \
                            or _dotted(m) != dtxt:
                        continue
                    if isinstance(m.ctx, ast.Store):
                        rebound = True
                    elif isinstance(m.ctx, ast.Load) and \
                            mline > call_end and used_line is None:
                        used_line = mline
                if used_line is not None and not rebound:
                    yield from emit(
                        used_line, "donate",
                        f"'{dtxt}' was donated to '{tgt}' at line "
                        f"{n.lineno} (donate_argnums position {pos}) and "
                        "is read here without being rebound: the buffer "
                        "was surrendered to XLA and may alias the "
                        "output; use the returned value instead")


# --------------------------------------------------------------------------
# R22: metric-name registry — perf/ledger name literals must be declared

_METRIC_REGISTRY: Optional[Tuple[frozenset, frozenset]] = None

# resolved call target -> which registry its first literal arg must hit
_R22_PERF_CALLS = frozenset({"ray_tpu.observability.perf.observe"})
_R22_LEDGER_CALLS = frozenset({"ray_tpu.observability.goodput.account",
                               "ray_tpu.observability.goodput.interval"})


def _metric_registry() -> Tuple[frozenset, frozenset]:
    """(PERF_HISTOGRAMS, LEDGER_CATEGORIES) from
    ``ray_tpu/observability/metric_names.py``.  The module is
    deliberately import-free, and exec'ing its source keeps the linter
    from dragging the observability package (config, runtime state)
    into a static-analysis process."""
    global _METRIC_REGISTRY
    if _METRIC_REGISTRY is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "observability", "metric_names.py")
        ns: Dict[str, object] = {}
        with open(path, encoding="utf-8") as f:
            exec(compile(f.read(), path, "exec"), ns)  # noqa: S102
        _METRIC_REGISTRY = (frozenset(ns["PERF_HISTOGRAMS"]),
                            frozenset(ns["LEDGER_CATEGORIES"]))
    return _METRIC_REGISTRY


def _resolved_call_target(node: ast.Call, ctx: FileContext
                          ) -> Optional[str]:
    """Fully-qualified dotted target of a call, resolving the head
    segment through the file's imports (``from ray_tpu.observability
    import perf`` makes ``perf.observe`` resolve to
    ``ray_tpu.observability.perf.observe``)."""
    full = _dotted(node.func)
    if not full:
        return None
    head, _, rest = full.partition(".")
    origin = ctx.import_origin.get(head)
    if origin:
        return origin + ("." + rest if rest else "")
    return full


@rule("R22", "metric-registry")
def check_metric_registry(ctx: FileContext) -> Iterator[Finding]:
    """A literal metric name passed to ``perf.observe(...)`` or a
    literal ledger category passed to ``goodput.account(...)`` /
    ``goodput.interval(...)`` that is not declared in
    ``ray_tpu/observability/metric_names.py``.  A typo'd name does not
    fail at runtime — it silently mints a parallel histogram family
    every consumer (head quantiles, ``ray-tpu top``, doctor baselines)
    ignores, and a misspelled category raises only when that code path
    finally runs.  Non-literal names are dynamic and out of scope."""
    perf_names, ledger_names = _metric_registry()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        target = _resolved_call_target(node, ctx)
        if target in _R22_PERF_CALLS:
            registry, kind, where = perf_names, "histogram", "PERF_HISTOGRAMS"
        elif target in _R22_LEDGER_CALLS:
            registry, kind, where = (ledger_names, "ledger category",
                                     "LEDGER_CATEGORIES")
        else:
            continue
        arg = node.args[0]
        if not isinstance(arg, ast.Constant) or \
                not isinstance(arg.value, str):
            continue  # dynamic name: statically unverifiable
        if arg.value in registry:
            continue
        if ctx.allowed(node.lineno, "R22", "metric-registry"):
            continue
        yield Finding(
            "R22", "metric-registry", ctx.relpath, node.lineno,
            f"{kind} {arg.value!r} is not declared in "
            f"ray_tpu/observability/metric_names.py ({where}): a typo "
            "here silently mints a parallel series no consumer reads — "
            "fix the name or declare it in the registry")


# --------------------------------------------------------------------------
# R26: actuator bypass — autopilot-owned knobs move only through apply()

_AUTOPILOT_KNOBS: Optional[frozenset] = None


def _autopilot_owned_knobs() -> frozenset:
    """Knob names from ``ray_tpu/autopilot/knobs.py`` (OWNED_KNOBS).
    Same exec-don't-import contract as :func:`_metric_registry`: the
    registry module is import-free by design, so the linter reads the
    ownership table without dragging the runtime in."""
    global _AUTOPILOT_KNOBS
    if _AUTOPILOT_KNOBS is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "autopilot", "knobs.py")
        ns: Dict[str, object] = {}
        with open(path, encoding="utf-8") as f:
            exec(compile(f.read(), path, "exec"), ns)  # noqa: S102
        _AUTOPILOT_KNOBS = frozenset(ns["OWNED_KNOBS"])
    return _AUTOPILOT_KNOBS


@rule("R26", "actuator-bypass")
def check_actuator_bypass(ctx: FileContext) -> Iterator[Finding]:
    """A runtime ``_config.set("<knob>", ...)`` write to an
    autopilot-owned knob (``ray_tpu/autopilot/knobs.py``) outside the
    guardrailed ``autopilot.actuators.apply()`` path.  Such a write
    forks control of the knob: the controller's journal no longer
    explains the value, its SLO watch/revert guarantee silently does
    not cover the foreign write, and the next policy pass may fight it.
    Tests that pin owned knobs run under the scoped allow profile in
    ``run_static_analysis.sh``; dynamic knob names are out of scope."""
    rel = ctx.relpath.replace("\\", "/")
    if "autopilot" in rel.split("/"):
        return  # the actuator layer is the single allowlisted write path
    owned = _autopilot_owned_knobs()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "set"):
            continue
        is_registry = (isinstance(node.func.value, ast.Name)
                       and _config_receiver(node.func.value.id, ctx))
        if not is_registry:
            is_registry = (_resolved_call_target(node, ctx)
                           == "ray_tpu._private.config._config.set")
        if not is_registry:
            continue
        arg = node.args[0]
        if not isinstance(arg, ast.Constant) or \
                not isinstance(arg.value, str):
            continue  # dynamic knob name: statically unverifiable
        if arg.value not in owned:
            continue
        if ctx.allowed(node.lineno, "R26", "actuator-bypass"):
            continue
        yield Finding(
            "R26", "actuator-bypass", ctx.relpath, node.lineno,
            f"'{arg.value}' is autopilot-owned (ray_tpu/autopilot/"
            f"knobs.py): a direct _config.set bypasses the guardrailed "
            f"actuator layer — no journal record, no bounds clamp, no "
            f"SLO watch/revert; go through ray_tpu.autopilot.actuators"
            f".apply()")


# --------------------------------------------------------------------------
# R23-R25: field-level thread-safety — whole-program lockset analysis
#
# All three rules consume ``ProjectIndex.field_plan()``: per shared
# attribute (``self._x`` / module global), every access site reachable
# from >=2 thread roots, with the *effective* lockset there (locks held
# lexically, unioned with the must-hold intersection over every call
# path from the thread root).  Under-approximation stance throughout:
# a lock only counts as held when provably held, a context only exists
# when the spawn edge resolved — so the rules can under-report but a
# reported witness is real modulo the documented suppressions.


def _field_site(rel: str, line: int) -> str:
    """lockwatch's ``pkg/file.py:line`` site format, so static witnesses
    and runtime lockwatch reports correlate by string."""
    return (f"{os.path.basename(os.path.dirname(rel))}/"
            f"{os.path.basename(rel)}:{line}")


def _ctx_label(plan: "_cg.FieldPlan", cname: str) -> str:
    """Human name for a thread context: ``main`` or the root's
    provenance plus its spawn/dispatch site."""
    root = plan.roots.get(cname)
    if root is None:
        return cname
    rel, line, how = root
    return f"{how} @ {_field_site(rel, line)}"


def _lockset_str(locks: Iterable[str]) -> str:
    inner = ", ".join(sorted(locks))
    return "[" + inner + "]" if inner else "[none]"


def _happens_before_spawn(plan: "_cg.FieldPlan", access: "_cg.FieldAccess",
                          other_ctx: str) -> bool:
    """*access* sits in a function that itself spawns *other_ctx*'s root
    at a later line: the classic single-writer-before-spawn handoff —
    Thread.start() publishes everything written before it."""
    for root, line in plan.spawns_in.get(access.fnq, ()):
        if root == other_ctx and access.line <= line:
            return True
    return False


def _guard_decl_for(idx: "_cg.ProjectIndex", plan: "_cg.FieldPlan",
                    key: str) -> Optional[Tuple[str, str, int]]:
    """The ``guarded-by`` declaration covering *key*: exact match, or one
    declared on a related class — the field is assigned (and declared) in
    a base-class ``__init__``, but accesses from subclass-defined methods
    key under the subclass (``Counter._values`` vs ``Metric._values``)."""
    hit = plan.guarded.get(key)
    if hit is not None or ":" not in key:
        return hit
    fld = key.split(":", 1)[1]
    if "." not in fld:
        return None
    kcls, attr = fld.rsplit(".", 1)
    for dkey in sorted(plan.guarded):
        if ":" not in dkey:
            continue
        dfld = dkey.split(":", 1)[1]
        if "." not in dfld:
            continue
        dcls, dattr = dfld.rsplit(".", 1)
        if dattr == attr and _classes_related(idx, kcls, dcls):
            return plan.guarded[dkey]
    return None


def _field_race_witness(plan: "_cg.FieldPlan", w: "_cg.FieldAccess",
                        o: "_cg.FieldAccess"
                        ) -> Optional[Tuple[str, str]]:
    """A (write-context, other-context) pair under which *w* and *o* can
    interleave with no common lock, or None.  Deterministic: contexts are
    scanned in sorted order, so the first witness is stable across runs."""
    for wc in sorted(w.ctxs):
        for oc in sorted(o.ctxs):
            if wc == oc:
                continue
            if w.ctxs[wc] & o.ctxs[oc]:
                continue
            if _happens_before_spawn(plan, w, oc) or \
                    _happens_before_spawn(plan, o, wc):
                continue
            return wc, oc
    return None


@project_rule("R23", "data-race")
def check_data_race(ctxs: List[FileContext], engine) -> Iterator[Finding]:
    """Whole-program data race: a shared attribute (``self._x`` or a
    module global) written in one thread context and read/written in
    another with an empty lockset intersection between the two sites.
    Thread contexts are the spawn roots the call graph proves distinct —
    ``threading.Thread`` targets, executor submits, RPC dispatch arms,
    ``Thread`` subclass ``run`` methods — plus ``main``.  Suppressed, to
    keep the rule honest: fields only touched during construction
    (immutable-after-init), writes that happen before the racing thread
    is spawned (single-writer handoff), bool/None fast-path flags (torn
    writes are impossible for a pointer-sized constant), atomic-style
    containers (``queue.Queue``, ``deque``, ``Event``, ...), and fields
    carrying a ``guarded-by`` declaration (R25 enforces those).  The
    witness names both thread roots in lockwatch's site format."""
    idx = engine.index(ctxs)
    plan = idx.field_plan()
    ctx_by_rel = {c.relpath: c for c in ctxs}
    for key in sorted(plan.accesses):
        if key in plan.flag_keys or \
                _guard_decl_for(idx, plan, key) is not None:
            continue
        sites = plan.accesses[key]
        emitted = False
        for w in sites:
            if emitted:
                break
            if w.mode not in ("write", "mutate"):
                continue
            for o in sites:
                wit = _field_race_witness(plan, w, o)
                if wit is None:
                    continue
                fctx = ctx_by_rel.get(w.rel)
                if fctx is not None and \
                        fctx.allowed(w.line, "R23", "data-race"):
                    break       # justified at the write: next write site
                wc, oc = wit
                yield Finding(
                    "R23", "data-race", w.rel, w.line,
                    f"data race on {_cg.field_display(key)}: "
                    f"{w.mode}@{_field_site(w.rel, w.line)} vs "
                    f"{o.mode}@{_field_site(o.rel, o.line)} with no common "
                    f"lock (contexts: {_ctx_label(plan, wc)} vs "
                    f"{_ctx_label(plan, oc)}; locks: "
                    f"{_lockset_str(w.ctxs[wc])} vs "
                    f"{_lockset_str(o.ctxs[oc])}) — guard both sites with "
                    "one lock and declare it with '# raylint: "
                    "guarded-by(<lock>)', or justify with '# raylint: "
                    "allow(data-race) <why>'")
                emitted = True
                break
    return


@project_rule("R24", "atomicity-split")
def check_atomicity_split(ctxs: List[FileContext],
                          engine) -> Iterator[Finding]:
    """Atomicity split on a shared attribute: a check-then-act
    (``if self._n < cap: ... self._n += 1`` with the test outside the
    write's critical section) or a read-modify-write whose read and
    dependent write hold no common lock — the lock was released between
    the two halves, so another thread can interleave and the decision
    acts on stale state.  Only fields that are actually shared are
    audited (a ``guarded-by`` declaration, or reachability from >=2
    thread contexts); construction-only code, atomic-style containers,
    and bool fast-path flags are exempt, and double-checked locking
    (re-read under the lock that guards the write) stays quiet."""
    idx = engine.index(ctxs)
    plan = idx.field_plan()
    seen: Set[Tuple[str, int, str]] = set()
    for fnq, key, rline, wline, kind in sorted(plan.splits):
        if key in plan.atomic_keys or key in plan.flag_keys:
            continue
        if fnq in plan.init_only or not plan.contexts.get(fnq):
            continue
        fn = idx.functions.get(fnq)
        if fn is None:
            continue
        shared = _guard_decl_for(idx, plan, key) is not None
        if not shared:
            names: Set[str] = set()
            for a in plan.accesses.get(key, ()):
                names.update(a.ctxs)
            shared = len(names) >= 2
        if not shared:
            continue
        rel = fn.ctx.relpath
        ident = (rel, wline, key)
        if ident in seen:
            continue
        seen.add(ident)
        if fn.ctx.allowed(wline, "R24", "atomicity-split"):
            continue
        yield Finding(
            "R24", "atomicity-split", rel, wline,
            f"atomicity split on {_cg.field_display(key)} ({kind}): the "
            f"read at {_field_site(rel, rline)} and the dependent write "
            f"at {_field_site(rel, wline)} hold no common lock — another "
            "thread can interleave between check and act; widen the "
            "critical section to cover both, or justify with "
            "'# raylint: allow(atomicity-split) <why>'")


def _base_leaf_names(idx: "_cg.ProjectIndex", name: str) -> Set[str]:
    """Transitive base-class leaf names of every class called *name*."""
    out: Set[str] = set()
    work = [name]
    while work:
        n = work.pop()
        for cls in idx.classes.values():
            if cls.name != n:
                continue
            for base in cls.bases:
                leaf = base.rsplit(".", 1)[-1]
                if leaf not in out:
                    out.add(leaf)
                    work.append(leaf)
    return out


def _classes_related(idx: "_cg.ProjectIndex", a: str, b: str) -> bool:
    return a == b or b in _base_leaf_names(idx, a) \
        or a in _base_leaf_names(idx, b)


def _field_lock_matches(idx: "_cg.ProjectIndex", decl: str,
                        held: frozenset) -> bool:
    """The declared lock is provably held: exact identity match, or the
    same attribute on a related class (a base-class method acquiring
    ``self._lock`` satisfies a subclass's declaration and vice versa —
    ``_lock_identity`` names locks after the *defining* class)."""
    if decl in held:
        return True
    dhead, _, dleaf = decl.rpartition(".")
    if not dhead or "." in dhead:
        return False        # module-global lock: identity match only
    for h in held:
        hhead, _, hleaf = h.rpartition(".")
        if hleaf == dleaf and hhead and "." not in hhead and \
                _classes_related(idx, dhead, hhead):
            return True
    return False


def _guard_lock_display(key: str, lock: str) -> str:
    """The lock as a developer would write it in the declaration —
    ``Cls.attr`` back to ``self.attr`` when the class matches the
    field's, a module-qualified global back to its bare name — so R25
    messages string-match lockwatch level-2 runtime reports."""
    head, _, leaf = lock.rpartition(".")
    fld = _cg.field_display(key)
    kcls = fld.rsplit(".", 1)[0] if ":" in key and "." in fld else ""
    if head and head == kcls:
        return "self." + leaf
    if "." in head:
        return leaf
    return lock


@project_rule("R25", "guarded-by")
def check_guarded_by(ctxs: List[FileContext], engine) -> Iterator[Finding]:
    """``# raylint: guarded-by(<lock>)`` enforcement, both directions.
    (a) Every access to a declared field must hold the declared lock —
    checked per thread context with the interprocedural must-hold
    lockset, so a caller-held lock satisfies the contract.  (b) A field
    the analysis proves multi-thread (>=2 contexts, at least one write)
    that is *consistently* locked must carry a declaration — the
    implicit convention becomes a machine-checked contract, and
    ``RAY_TPU_LOCKWATCH=2`` samples the same declarations at runtime,
    printing violations in this rule's format so static and live
    findings correlate by string.  Inconsistently-locked fields are
    R23's jurisdiction, not a missing declaration."""
    from ray_tpu.devtools import lockwatch as _lw
    idx = engine.index(ctxs)
    plan = idx.field_plan()
    ctx_by_rel = {c.relpath: c for c in ctxs}
    # (a) declared fields: the named lock must be held at every site
    # (accesses keyed under a subclass resolve to the base declaration)
    for key in sorted(plan.accesses):
        decl = _guard_decl_for(idx, plan, key)
        if decl is None:
            continue
        lock, drel, dline = decl
        disp = _guard_lock_display(key, lock)
        for a in plan.accesses.get(key, ()):
            bad = [cn for cn in sorted(a.ctxs)
                   if not _field_lock_matches(idx, lock, a.ctxs[cn])]
            if not bad:
                continue
            fctx = ctx_by_rel.get(a.rel)
            if fctx is not None and \
                    fctx.allowed(a.line, "R25", "guarded-by"):
                continue
            yield Finding(
                "R25", "guarded-by", a.rel, a.line,
                _lw.format_guard(_cg.field_display(key), disp)
                + f" (declared at {drel}:{dline}; context "
                f"{_ctx_label(plan, bad[0])}, locks "
                f"{_lockset_str(a.ctxs[bad[0]])}) — acquire the declared "
                "lock, fix the declaration, or justify with '# raylint: "
                "allow(guarded-by) <why>'")
    # (b) proved-shared, consistently-locked fields need a declaration
    for key in sorted(plan.accesses):
        if key in plan.atomic_keys or key in plan.flag_keys \
                or _guard_decl_for(idx, plan, key) is not None:
            continue
        sites = plan.accesses[key]
        names: Set[str] = set()
        for a in sites:
            names.update(a.ctxs)
        if len(names) < 2:
            continue
        writes = sorted((a for a in sites if a.mode in ("write", "mutate")),
                        key=lambda a: (a.rel, a.line))
        if not writes:
            continue
        common: Optional[Set[str]] = None
        for a in sites:
            for held in a.ctxs.values():
                common = set(held) if common is None else (common & held)
        if not common:
            continue        # unlocked somewhere: R23 reports the race
        w = writes[0]
        fctx = ctx_by_rel.get(w.rel)
        if fctx is not None and fctx.allowed(w.line, "R25", "guarded-by"):
            continue
        disp = _guard_lock_display(key, sorted(common)[0])
        yield Finding(
            "R25", "guarded-by", w.rel, w.line,
            f"shared field {_cg.field_display(key)} is reached from "
            f"{len(names)} thread contexts "
            f"({', '.join(_ctx_label(plan, n) for n in sorted(names))}) "
            f"and is consistently locked under {disp}, but carries no "
            f"declaration — annotate the field's assignment with "
            f"'# raylint: guarded-by({disp})' so the convention is "
            "machine-checked here and sampled live under "
            "RAY_TPU_LOCKWATCH=2")


# --------------------------------------------------------------------------
# R27-R29: static SPMD sharding analysis over the shardprop model
#
# All three rules share one ShardModel per run (engine.shard_model); the
# per-file facts ride the incremental cache keyed by content hash, like
# the stitch and field facts.  The propagation lattice is constant-or-top:
# dynamic specs, open mesh/rules universes and starred parts degrade to
# silence — under-report, never invent.

_R27_AXIS_KIND = {
    "spec": "PartitionSpec",
    "rules-table": "ShardingRules table value",
    "override": "with_overrides() value",
}


@project_rule("R27", "mesh-spec")
def check_mesh_spec(ctxs: List[FileContext],
                    engine: "LintEngine") -> Iterator[Finding]:
    """Mesh/spec consistency over the abstract sharding model: a
    PartitionSpec (or rules-table / with_overrides value) naming a mesh
    axis that no AXIS_ORDER or Mesh(...) construction declares, one mesh
    axis bound to two dims of a single spec, shard_map in_specs arity
    differing from the mapped callee's parameter count, and logical-axis
    names absent from every reachable ShardingRules table.  A
    one-character axis typo is exactly what ShardingRules.spec() would
    otherwise silently replicate (its strict= mode is the runtime half of
    this check); unresolvable specs and open universes degrade to
    silence.  Justify exceptions with
    '# raylint: allow(mesh-spec) <why>'."""
    model = engine.shard_model(ctxs)
    ctx_by_rel = {c.relpath: c for c in ctxs}
    mesh_known = model.mesh_closed()
    rules_known = model.rules_closed()
    for rel in sorted(model.facts):
        fctx = ctx_by_rel.get(rel)
        facts = model.facts[rel]

        def allowed(line: int) -> bool:
            return fctx is not None and fctx.allowed(line, "R27",
                                                     "mesh-spec")

        if mesh_known:
            for line, ax, kind in facts["axis_sites"]:
                if ax in model.mesh_axes or allowed(line):
                    continue
                yield Finding(
                    "R27", "mesh-spec", rel, line,
                    f"{_R27_AXIS_KIND.get(kind, kind)} names mesh axis "
                    f"'{ax}', but no AXIS_ORDER or Mesh(...) in the tree "
                    f"declares it (known axes: "
                    f"{', '.join(sorted(model.mesh_axes))}) — jax raises "
                    "at trace time or the dimension silently replicates")
        for line, ax in facts["dup_sites"]:
            if allowed(line):
                continue
            yield Finding(
                "R27", "mesh-spec", rel, line,
                f"mesh axis '{ax}' is bound to two dimensions of a single "
                "PartitionSpec — jax rejects the spec at trace time; use "
                "a tuple (('a', 'b')) to co-shard one dimension instead")
        for line, got, want, callee in facts["arity_sites"]:
            if allowed(line):
                continue
            yield Finding(
                "R27", "mesh-spec", rel, line,
                f"shard_map in_specs carries {got} spec(s) but the mapped "
                f"callable '{callee}' takes {want} positional "
                "argument(s) — the mismatch only surfaces at trace time")
        if rules_known:
            for line, name, src in facts["logical_sites"]:
                if name in model.logical_names or allowed(line):
                    continue
                yield Finding(
                    "R27", "mesh-spec", rel, line,
                    f"logical axis '{name}' is in no reachable "
                    "ShardingRules table (DEFAULT_RULES + with_overrides) "
                    "— ShardingRules.spec() silently replicates unknown "
                    "names, so this dimension would never be sharded")


@project_rule("R28", "implicit-reshard")
def check_implicit_reshard(ctxs: List[FileContext],
                           engine: "LintEngine") -> Iterator[Finding]:
    """Implicit reshard across a jitted boundary: an array placed with
    one sharding (device_put / make_array under a NamedSharding) and then
    passed to a shard_map/pjit callable whose in_specs/in_shardings pin a
    different spec — XLA inserts a silent resharding collective on the
    hot path; also donated buffers whose donation is wasted because the
    out-spec differs from the donated argument's in-spec.  Both sides
    must be statically provable (same scope chain, fully-constant specs)
    for the rule to fire.  Justify deliberate reshards with
    '# raylint: allow(implicit-reshard) <why>'."""
    model = engine.shard_model(ctxs)
    ctx_by_rel = {c.relpath: c for c in ctxs}
    for rel in sorted(model.facts):
        fctx = ctx_by_rel.get(rel)
        facts = model.facts[rel]
        for line, pos, got, want, callee in facts["reshard_sites"]:
            if fctx is not None and fctx.allowed(line, "R28",
                                                 "implicit-reshard"):
                continue
            yield Finding(
                "R28", "implicit-reshard", rel, line,
                f"argument {pos} of '{callee}' was placed as {got} but its "
                f"in_specs expect {want}: XLA inserts a silent resharding "
                "collective at this boundary on every call — place the "
                "array with the consumer's spec (or annotate why not)")
        for line, pos, got, want in facts["donate_sites"]:
            if fctx is not None and fctx.allowed(line, "R28",
                                                 "implicit-reshard"):
                continue
            yield Finding(
                "R28", "implicit-reshard", rel, line,
                f"donated argument {pos} enters as {got} but the result "
                f"leaves as {want}: the layouts differ, so XLA cannot "
                "reuse the donated buffer and the donation is wasted — "
                "align out_shardings with the donated in_sharding")


@project_rule("R29", "comms-manifest")
def check_comms_manifest(ctxs: List[FileContext],
                         engine: "LintEngine") -> Iterator[Finding]:
    """Static collective-cost manifest: every explicit ray_tpu.collective
    op (keyed by group name) and every jax.lax collective with a resolved
    mesh axis (keyed axis:<name>) is compiled into a plan with its busbw
    wire-factor formula — written via --comms-manifest and cross-checked
    at runtime by ray_tpu.doctor --comms-baseline ('__manifest__' key),
    which reports ledgered ops absent from the plan as drift.  The rule
    itself flags collectives over a mesh axis that no mesh in the tree
    declares: such an op can never appear in the plan, so it would always
    report as unplanned drift.  Justify with
    '# raylint: allow(comms-manifest) <why>'."""
    model = engine.shard_model(ctxs)
    engine.comms_manifest = _sp.build_manifest(model)
    if not model.mesh_closed():
        return
    ctx_by_rel = {c.relpath: c for c in ctxs}
    for rel in sorted(model.facts):
        fctx = ctx_by_rel.get(rel)
        for line, op, axis in model.facts[rel]["lax_sites"]:
            if axis == _sp.UNKNOWN or axis in model.mesh_axes:
                continue
            if fctx is not None and fctx.allowed(line, "R29",
                                                 "comms-manifest"):
                continue
            yield Finding(
                "R29", "comms-manifest", rel, line,
                f"collective '{op}' runs over mesh axis '{axis}', which "
                f"no AXIS_ORDER or Mesh(...) in the tree declares (known "
                f"axes: {', '.join(sorted(model.mesh_axes))}) — the op "
                "cannot be planned in comms_manifest.json and would "
                "always surface as unplanned drift at runtime")


# --------------------------------------------------------------------------
# engine

class LintEngine:
    def __init__(self, roots: Iterable[str], baseline_path: Optional[str] = None,
                 only_rules: Optional[Set[str]] = None,
                 proto_pairs: Optional[List[Tuple[str, str, str]]] = None,
                 allow_in: Optional[List[Tuple[str, Set[str]]]] = None,
                 changed_only: Optional[Set[str]] = None,
                 cache: bool = False):
        self.roots = [os.path.abspath(r) for r in roots]
        self.baseline = self._load_baseline(baseline_path)
        self.only_rules = only_rules
        # explicit (proto_path, pb2_path, relpath) triples override R6's
        # autodiscovery — the drift tests point this at mutated fixtures
        self.proto_pairs = proto_pairs
        # scoped allow profile: (path prefix, {rule ids/tags}) pairs —
        # findings under the prefix for those rules are suppressed (the
        # gate relaxes a few rules for tests/ without allowlisting files)
        self.allow_in = allow_in or []
        # incremental mode: the whole tree is still parsed (project rules
        # need global context) but only findings in these repo-relative
        # paths are reported
        self.changed_only = changed_only
        # incremental analysis cache: valid only for full-rule runs (a
        # partial --rules run would poison the stored finding sets)
        self.cache_enabled = cache and only_rules is None
        # (file hits, files total, project-level hit) after run()
        self.cache_stats: Optional[Tuple[int, int, bool]] = None
        # (stitch-fact replay hits, files stitched) after an index build —
        # None when no project rule forced the graph
        self.stitch_stats: Optional[Tuple[int, int]] = None
        # (field-fact replay hits, files scanned) after a field-plan
        # build — None when no field rule (R23-R25) forced it
        self.field_stats: Optional[Tuple[int, int]] = None
        # wall time per project rule id (plus "graph" for the index build)
        self.rule_times: Dict[str, float] = {}
        self.errors: List[str] = []
        self._index: Optional[_cg.ProjectIndex] = None
        # hash-validated per-file stitch facts replayed from the cache
        self._stitch_cache: Dict[str, dict] = {}
        # hash-validated per-file field-safety facts (R23-R25) replayed
        # from the cache
        self._field_cache: Dict[str, dict] = {}
        # hash-validated per-file SPMD shard facts (R27-R29) replayed
        # from the cache
        self._shard_cache: Dict[str, dict] = {}
        # (shard-fact replay hits, files scanned) after a shard-model
        # build — None when no SPMD rule (R27-R29) forced it
        self.shard_stats: Optional[Tuple[int, int]] = None
        self._shard_model: Optional[_sp.ShardModel] = None
        # static collective plan (R29) — built by the R29 rule or
        # replayed from the project cache; --comms-manifest writes it
        self.comms_manifest: Optional[dict] = None

    def index(self, ctxs: List[FileContext]) -> _cg.ProjectIndex:
        """Whole-program symbol table / call graph, built once per run and
        shared by every interprocedural rule (R10-R12, R19-R20, R23-R25)."""
        if self._index is None:
            self._index = _cg.ProjectIndex(
                ctxs, stitch_facts=self._stitch_cache,
                field_facts=self._field_cache)
            self.stitch_stats = (self._index.stitch_hits,
                                 len(self._index.stitch_facts))
        return self._index

    def shard_model(self, ctxs: List[FileContext]) -> _sp.ShardModel:
        """Whole-tree SPMD sharding model, built once per run and shared
        by R27-R29, with hash-validated per-file fact replay exactly like
        the stitch/field layers."""
        if self._shard_model is None:
            self._shard_model = _sp.ShardModel(
                ctxs, cached=self._shard_cache)
            self.shard_stats = (self._shard_model.hits,
                                len(self._shard_model.facts))
        return self._shard_model

    @staticmethod
    def _load_baseline(path: Optional[str]) -> Set[Tuple[str, str]]:
        entries: Set[Tuple[str, str]] = set()
        if not path or not os.path.exists(path):
            return entries
        with open(path, encoding="utf-8") as f:
            for raw in f:
                line = raw.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split(None, 1)
                if len(parts) == 2:
                    entries.add((parts[0], parts[1].strip()))
        return entries

    def _want(self, rule_id: str, tag: str) -> bool:
        return not self.only_rules or \
            bool({rule_id, tag} & self.only_rules)

    def _iter_files(self) -> Iterator[Tuple[str, str]]:
        for root in self.roots:
            if os.path.isfile(root):
                yield root, os.path.basename(root)
                continue
            base = os.path.dirname(root.rstrip(os.sep))
            for dirpath, dirnames, filenames in os.walk(root):
                # devtools/fixtures holds deliberately-findings-bearing
                # corpus files for --self-check; only an explicit root
                # pointing inside it lints them
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__", ".git",
                                                  "fixtures"))
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        full = os.path.join(dirpath, fname)
                        yield full, os.path.relpath(full, base)

    # -- incremental analysis cache ----------------------------------------
    #
    # Derived artifacts (per-file file-rule findings; whole-tree project
    # findings) are keyed on content hashes, never on mtimes.  Re-parsing
    # is CHEAPER than unpickling trees on this corpus (measured: ast.parse
    # 0.66s vs pickle.load 1.0s for 181 files), so the cache deliberately
    # stores findings, not parse trees: a warm run is hash + emit.

    _salt: Optional[str] = None

    @classmethod
    def _engine_salt(cls) -> str:
        """Content hash of the analysis code itself: any edit to the
        linter, call-graph, or dataflow layers invalidates every entry."""
        if cls._salt is None:
            from ray_tpu.devtools import lockwatch as _lw
            h = hashlib.sha256(sys.version.encode())
            for mod_file in (__file__, _cg.__file__, _df.__file__,
                             _sp.__file__, _lw.__file__):
                try:
                    with open(mod_file, "rb") as f:
                        h.update(f.read())
                except OSError:
                    h.update(mod_file.encode())
            cls._salt = h.hexdigest()
        return cls._salt

    @staticmethod
    def _cache_path() -> str:
        env = os.environ.get("RAYLINT_CACHE")
        if env:
            return env
        uid = getattr(os, "getuid", lambda: 0)()
        return os.path.join(tempfile.gettempdir(),
                            f"raylint-cache-{uid}.json")

    def _cache_load(self) -> dict:
        try:
            with open(self._cache_path(), encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {}
        return data if data.get("salt") == self._engine_salt() else {}

    def _cache_store(self, data: dict) -> None:
        path = self._cache_path()
        try:
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(path) or ".", prefix=".raylint-cache-")
        except OSError:
            return
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(data, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def run(self) -> List[Finding]:
        sources: List[Tuple[str, str, str]] = []
        for path, rel in self._iter_files():
            try:
                with open(path, encoding="utf-8") as f:
                    sources.append((path, rel, f.read()))
            except (OSError, UnicodeDecodeError) as e:
                self.errors.append(f"{rel}: unreadable: {e}")
        findings = self._execute(sources)
        findings = [f for f in findings
                    if (f.rule, f.path) not in self.baseline]
        if self.allow_in:
            findings = [f for f in findings
                        if not any(
                            f.path.replace("\\", "/").startswith(prefix) and
                            ({f.rule, f.tag, "all"} & rules)
                            for prefix, rules in self.allow_in)]
        if self.changed_only is not None:
            changed = {p.replace("\\", "/") for p in self.changed_only}
            findings = [f for f in findings
                        if f.path.replace("\\", "/") in changed]
        # nested loops can both see one sleep/handler — report each site once
        findings = sorted(set(findings),
                          key=lambda f: (f.path, f.line, f.rule))
        return findings

    def _execute(self, sources: List[Tuple[str, str, str]]) -> List[Finding]:
        """Parse + run rules, consulting the incremental cache when on.
        Returns raw (pre-baseline, pre-allow-in) findings."""
        cache = self._cache_load() if self.cache_enabled else None
        hashes = {rel: hashlib.sha256(src.encode()).hexdigest()
                  for _p, rel, src in sources}
        tree_key = None
        if cache is not None:
            tree_key = hashlib.sha256(
                json.dumps(sorted(hashes.items())).encode()).hexdigest()
            proj = cache.get("project") or {}
            if proj.get("tree_key") == tree_key:
                # whole-tree hit: nothing changed since the stored run, so
                # the project-rule findings (and everything else) replay
                # without a single ast.parse
                self.cache_stats = (len(sources), len(sources), True)
                self.errors.extend(proj.get("errors") or [])
                self.comms_manifest = proj.get("manifest")
                return [Finding(**d) for d in proj.get("findings") or []]
        ctxs: List[FileContext] = []
        file_findings: List[Finding] = []
        per_file: Dict[str, List[dict]] = {}
        cached_files = (cache.get("files") if cache is not None else {}) or {}
        hits = 0
        for path, rel, src in sources:
            try:
                ctx = FileContext(path, rel, src)
            except SyntaxError as e:
                self.errors.append(f"{rel}: unparseable: {e}")
                continue
            ctxs.append(ctx)
            ent = cached_files.get(rel)
            if cache is not None and ent and ent.get("hash") == hashes[rel]:
                hits += 1
                per_file[rel] = ent.get("findings") or []
                file_findings.extend(Finding(**d) for d in per_file[rel])
                continue
            mine: List[Finding] = []
            for rule_id, tag, fn in RULES:
                if self._want(rule_id, tag):
                    mine.extend(fn(ctx))
            file_findings.extend(mine)
            per_file[rel] = [f.to_json() for f in mine]
        # replay cross-process stitch facts for unchanged files: the graph
        # is still rebuilt (ast node identity can't be cached), but the
        # per-file send/dispatcher scans — the expensive half — are not
        cached_stitch = (cache.get("stitch") if cache is not None else
                         None) or {}
        self._stitch_cache = {
            rel: ent.get("facts") or {"sends": [], "dispatchers": []}
            for rel, ent in cached_stitch.items()
            if rel in hashes and ent.get("hash") == hashes[rel]}
        # same replay for the field-safety facts (R23-R25): per-file
        # access/split/guarded records are pure functions of one file's
        # source, so a matching content hash makes them valid verbatim
        cached_fields = (cache.get("fields") if cache is not None else
                         None) or {}
        self._field_cache = {
            rel: ent["facts"]
            for rel, ent in cached_fields.items()
            if rel in hashes and ent.get("hash") == hashes[rel]
            and ent.get("facts") is not None}
        # same replay for the SPMD shard facts (R27-R29): per-file spec /
        # mesh / collective-site records are pure functions of one file's
        # source, so a matching content hash makes them valid verbatim
        cached_shard = (cache.get("shard") if cache is not None else
                        None) or {}
        self._shard_cache = {
            rel: ent["facts"]
            for rel, ent in cached_shard.items()
            if rel in hashes and ent.get("hash") == hashes[rel]
            and ent.get("facts") is not None}
        proj_findings: List[Finding] = []
        if self.only_rules is None:
            t0 = time.perf_counter()
            self.index(ctxs)
            self.rule_times["graph"] = time.perf_counter() - t0
        for rule_id, tag, fn in PROJECT_RULES:
            if self._want(rule_id, tag):
                t0 = time.perf_counter()
                proj_findings.extend(fn(ctxs, self))
                self.rule_times[rule_id] = time.perf_counter() - t0
        if self._index is not None and self._index.field_facts:
            self.field_stats = (self._index.field_hits,
                                len(self._index.field_facts))
        if cache is not None:
            self.cache_stats = (hits, len(sources), False)
            # merge, don't replace: entries for files outside this run's
            # roots (another checkout, another root set) stay valid —
            # their content hashes still guard them
            merged = dict(cached_files)
            merged.update({rel: {"hash": hashes[rel],
                                 "findings": per_file[rel]}
                           for rel in per_file})
            stitch = dict(cached_stitch)
            if self._index is not None:
                stitch.update({rel: {"hash": hashes[rel], "facts": facts}
                               for rel, facts in
                               self._index.stitch_facts.items()
                               if rel in hashes})
            fields = dict(cached_fields)
            if self._index is not None:
                fields.update({rel: {"hash": hashes[rel], "facts": facts}
                               for rel, facts in
                               self._index.field_facts.items()
                               if rel in hashes})
            shard = dict(cached_shard)
            if self._shard_model is not None:
                shard.update({rel: {"hash": hashes[rel], "facts": facts}
                              for rel, facts in
                              self._shard_model.facts.items()
                              if rel in hashes})
            self._cache_store({
                "salt": self._engine_salt(),
                "files": merged,
                "stitch": stitch,
                "fields": fields,
                "shard": shard,
                "project": {
                    "tree_key": tree_key,
                    "findings": [f.to_json()
                                 for f in file_findings + proj_findings],
                    "errors": list(self.errors),
                    "manifest": self.comms_manifest},
            })
        return file_findings + proj_findings


def rule_listing() -> List[dict]:
    """Machine-readable registry listing (``--rules`` with no value).

    ``run_static_analysis.sh`` and the docs regeneration check consume
    this, so the script header and the ARCHITECTURE.md rule table can
    never drift from the rules actually registered."""
    out = []
    for kind, reg in (("file", RULES), ("project", PROJECT_RULES)):
        for rule_id, tag, fn in reg:
            doc = " ".join((fn.__doc__ or "").strip().split())
            out.append({"id": rule_id, "tag": tag, "kind": kind,
                        "summary": doc.split(". ")[0][:240],
                        "doc": doc})
    out.sort(key=lambda r: int(r["id"][1:]))
    return out


def sarif_log(findings: List[Finding]) -> dict:
    """Findings as a SARIF 2.1.0 log object (one run, one driver).  The
    rule metadata comes straight from :func:`rule_listing`, so the SARIF
    ``rules`` array can never drift from the registry."""
    rules = [{
        "id": r["id"],
        "name": r["tag"],
        "shortDescription": {"text": r["summary"]},
        "fullDescription": {"text": r["doc"]},
        # rule table anchor in the repo docs — consumers resolve it
        # against the checkout the log was produced from
        "helpUri": f"ARCHITECTURE.md#{r['id'].lower()}-{r['tag']}",
    } for r in rule_listing()]
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = [{
        "ruleId": f.rule,
        "ruleIndex": index.get(f.rule, -1),
        "level": "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f.path.replace("\\", "/"),
                    "uriBaseId": "SRCROOT"},
                "region": {"startLine": f.line},
            },
        }],
    } for f in findings]
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "raylint",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def _changed_files(ref: str) -> Optional[Set[str]]:
    """Repo-relative ``*.py`` paths changed vs *ref* plus untracked files,
    or None when git is unavailable (caller falls back to a full lint)."""
    import subprocess
    files: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "--diff-filter=d", ref],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        files |= {line.strip() for line in proc.stdout.splitlines()
                  if line.strip().endswith(".py")}
    return files


def _run_self_check() -> int:
    """Round-trip the shipped fixture corpus against expected.json: every
    expected finding must fire at its exact line, and nothing else may."""
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
    expected_path = os.path.join(base, "expected.json")
    with open(expected_path, encoding="utf-8") as f:
        expected = json.load(f)
    engine = LintEngine([base])
    got = {(f.rule, f.path, f.line) for f in engine.run()}
    want = {(e["rule"], e["path"], e["line"]) for e in expected}
    for rule_id, path, line in sorted(want - got):
        print(f"self-check: MISSING expected finding "
              f"{rule_id} at {path}:{line}")
    for rule_id, path, line in sorted(got - want):
        print(f"self-check: UNEXPECTED finding {rule_id} at {path}:{line}")
    for err in engine.errors:
        print(f"self-check: warning: {err}")
    if got == want:
        print(f"self-check: OK ({len(want)} fixture findings round-trip)")
        return 0
    print("self-check: FAIL")
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="framework-aware static analysis for ray_tpu")
    parser.add_argument("roots", nargs="*", default=["ray_tpu"],
                        help="files or directories to lint")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as a JSON array")
    parser.add_argument("--baseline", default=None,
                        help="allowlist file of 'RULE path' lines")
    parser.add_argument("--rules", nargs="?", const="<list>", default=None,
                        metavar="IDS",
                        help="comma-separated rule ids/tags to run "
                             "(default: all); with no value, print the "
                             "machine-readable rule listing as JSON")
    parser.add_argument("--changed", nargs="?", const="HEAD", default=None,
                        metavar="REF",
                        help="incremental mode: parse the whole tree "
                             "(project rules need it) but only report "
                             "findings in files changed vs REF "
                             "(git diff + untracked; default HEAD)")
    parser.add_argument("--allow-in", action="append", default=[],
                        metavar="PREFIX:RULES",
                        help="scoped allow profile, e.g. "
                             "'tests/:R12,bare-retry' — suppress those "
                             "rules under the path prefix (repeatable)")
    parser.add_argument("--self-check", action="store_true",
                        help="lint the shipped fixture corpus and verify "
                             "it round-trips expected.json exactly")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the content-hash incremental cache "
                             "(default location: raylint-cache-<uid>.json "
                             "in the system temp dir, override with "
                             "$RAYLINT_CACHE)")
    parser.add_argument("--sarif", default=None, metavar="OUT.json",
                        help="additionally write findings as a SARIF 2.1.0 "
                             "log to OUT.json (machine-consumable for "
                             "code-scanning UIs)")
    parser.add_argument("--comms-manifest", default=None, metavar="OUT.json",
                        help="additionally write the R29 static "
                             "collective-cost manifest (planned ops per "
                             "group / mesh axis with busbw wire factors) "
                             "to OUT.json; ray_tpu.doctor --comms-baseline "
                             "cross-checks the runtime ledger against it "
                             "via the '__manifest__' baseline key")
    parser.add_argument("--write-baseline", default=None, metavar="FILE",
                        help="write current findings as a baseline and exit 0")
    args = parser.parse_args(argv)

    if args.self_check:
        return _run_self_check()
    if args.rules == "<list>":
        print(json.dumps(rule_listing(), indent=2))
        return 0

    only = {r.strip() for r in args.rules.split(",")} if args.rules else None
    allow_in = []
    for spec in args.allow_in:
        prefix, _, rules_csv = spec.partition(":")
        if not prefix or not rules_csv:
            parser.error(f"--allow-in wants PREFIX:RULES, got {spec!r}")
        allow_in.append((prefix, {r.strip() for r in rules_csv.split(",")}))
    changed_only = None
    if args.changed is not None:
        changed_only = _changed_files(args.changed)
        if changed_only is not None and not changed_only:
            # nothing changed: cheap exit, same contract as a clean lint
            print("raylint: 0 finding(s) (no changed *.py files)"
                  if not args.json else "[]")
            return 0
    engine = LintEngine(args.roots or ["ray_tpu"], args.baseline, only,
                        allow_in=allow_in, changed_only=changed_only,
                        cache=not args.no_cache)
    findings = engine.run()
    if engine.cache_stats is not None:
        hits, total, warm = engine.cache_stats
        if warm:
            stitch = "stitch replayed"
        elif engine.stitch_stats is not None:
            stitch = "stitch {}/{}".format(*engine.stitch_stats)
        else:
            stitch = "stitch skipped"
        if warm:
            fields = "fields replayed"
        elif engine.field_stats is not None:
            fields = "fields {}/{}".format(*engine.field_stats)
        else:
            fields = "fields skipped"
        if warm:
            shard = "shard replayed"
        elif engine.shard_stats is not None:
            shard = "shard {}/{}".format(*engine.shard_stats)
        else:
            shard = "shard skipped"
        print(f"raylint-cache: {hits}/{total} file hits, "
              f"project {'hit' if warm else 'miss'}, {stitch}, {fields}, "
              f"{shard}",
              file=sys.stderr)
    if engine.rule_times:
        total_t = sum(engine.rule_times.values())
        parts = " ".join(f"{k} {v:.2f}s" for k, v in
                         sorted(engine.rule_times.items(),
                                key=lambda kv: -kv[1]))
        print(f"raylint-times: total {total_t:.2f}s {parts}",
              file=sys.stderr)
        if total_t > 1.0:
            for k, v in sorted(engine.rule_times.items()):
                if k != "graph" and v > 0.3 * total_t:
                    print(f"raylint-times: WARNING {k} took "
                          f"{v:.2f}s ({v / total_t:.0%} of project-rule "
                          "time)", file=sys.stderr)

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            f.write("# raylint baseline — tolerated pre-existing findings\n")
            for rule_id, path in sorted({(x.rule, x.path) for x in findings}):
                f.write(f"{rule_id} {path}\n")
        print(f"wrote {args.write_baseline} "
              f"({len(findings)} findings baselined)")
        return 0

    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(sarif_log(findings), f, indent=2)
        print(f"raylint: sarif log written to {args.sarif}",
              file=sys.stderr)

    if args.comms_manifest:
        manifest = engine.comms_manifest or {
            "version": 1, "tool": "raylint/R29", "mesh_axes": [],
            "unresolved_sites": 0, "groups": {}}
        with open(args.comms_manifest, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=2)
        n_groups = len(manifest.get("groups") or {})
        n_ops = sum(len(ops) for ops in (manifest.get("groups")
                                         or {}).values())
        print(f"raylint: comms manifest written to {args.comms_manifest} "
              f"({n_groups} group(s), {n_ops} planned op kind(s))",
              file=sys.stderr)

    if args.json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
        print(f"raylint: {len(findings)} finding(s)"
              + (f" ({summary})" if summary else ""))
        for err in engine.errors:
            print(f"raylint: warning: {err}")
    return 1 if findings else 0
