"""CLI entry point: ``python -m ray_tpu.devtools.lint [roots...]``.

Thin shim over :mod:`ray_tpu.devtools.linter` so the module path reads as
a command.  Exit status 0 = clean, 1 = findings, 2 = usage error.
"""

import sys

from ray_tpu.devtools.linter import main

if __name__ == "__main__":
    sys.exit(main())
