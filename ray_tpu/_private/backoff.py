"""Unified retry / backoff / deadline policy + per-peer circuit breaker.

Every retry loop in the runtime (RpcClient connect, StateClient reconnect
and call retry, heartbeat misses, task resubmission, borrow-protocol calls)
goes through :class:`BackoffPolicy` instead of hand-rolled
``time.sleep``-in-a-loop (raylint R7 flags those). The policy is the
composition the reference spreads across ``ray_config_def.h`` knobs:

- exponential backoff with **full jitter** (AWS-style: ``delay =
  uniform(0, min(max, base * mult**attempt))``) so synchronized failures
  don't retry in lockstep;
- an optional **per-attempt timeout** (each RPC attempt gets at most this);
- an overall **deadline budget** — retries stop when the budget is spent,
  not after a magic attempt count;
- **retryable-error classification**: connection/timeout faults retry,
  remote handler errors (``RpcRemoteError``) never do.

:class:`CircuitBreaker` / :class:`BreakerBoard` add the per-peer fail-fast
layer: after ``failure_threshold`` consecutive failures a peer's breaker
opens and callers shed load immediately instead of timing out every push;
after ``reset_s`` one probe is allowed through (half-open) and its outcome
closes or re-opens the breaker.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

from ray_tpu._private.config import _config

__all__ = ["BackoffPolicy", "BackoffState", "CircuitBreaker", "BreakerBoard",
           "retry_call", "RETRYABLE_DEFAULT"]

#: Errors that are retryable by default: transport-level faults. Notably
#: NOT RpcRemoteError (the peer's handler ran and raised — retrying would
#: re-execute side effects) — it subclasses RuntimeError, not OSError.
RETRYABLE_DEFAULT: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError)


class BackoffPolicy:
    """Immutable retry policy; ``start()`` yields the per-sequence state.

    ``None`` parameters fall back to the ``backoff_*`` config knobs at
    ``start()`` time, so env/system-config overrides apply without
    rebuilding policies. ``deadline_s=0`` / ``max_attempts=0`` mean
    unlimited; at least one should be bounded in production paths.
    """

    def __init__(self, base_s: Optional[float] = None,
                 max_s: Optional[float] = None,
                 multiplier: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 max_attempts: int = 0,
                 attempt_timeout_s: Optional[float] = None,
                 retryable: Tuple[Type[BaseException], ...] = RETRYABLE_DEFAULT,
                 jitter: bool = True,
                 seed: Optional[int] = None,
                 label: str = ""):
        self.base_s = base_s
        self.max_s = max_s
        self.multiplier = multiplier
        self.deadline_s = deadline_s
        self.max_attempts = max_attempts
        self.attempt_timeout_s = attempt_timeout_s
        self.retryable = retryable
        self.jitter = jitter
        self.seed = seed
        self.label = label  # metrics site tag for backoff_retries_total

    def classify(self, exc: BaseException) -> bool:
        """True when ``exc`` should be retried under this policy."""
        return isinstance(exc, self.retryable)

    def _resolved(self):
        base = (self.base_s if self.base_s is not None
                else _config.get("backoff_base_ms") / 1000.0)
        cap = (self.max_s if self.max_s is not None
               else _config.get("backoff_max_ms") / 1000.0)
        mult = (self.multiplier if self.multiplier is not None
                else _config.get("backoff_multiplier"))
        deadline = (self.deadline_s if self.deadline_s is not None
                    else _config.get("backoff_deadline_s"))
        return base, cap, mult, deadline

    def delay_for(self, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
        """Backoff delay before retry number ``attempt`` (0-based), with
        full jitter. Usable standalone (e.g. Timer-based resubmission)."""
        base, cap, mult, _ = self._resolved()
        upper = min(cap, base * (mult ** attempt))
        if not self.jitter:
            return upper
        return (rng or _rng).uniform(0.0, upper)

    def start(self, clock: Callable[[], float] = time.monotonic
              ) -> "BackoffState":
        base, cap, mult, deadline = self._resolved()
        return BackoffState(self, base, cap, mult, deadline, clock)


class BackoffState:
    """One retry sequence: tracks attempts and the deadline budget.

    Loop shape::

        state = policy.start()
        while True:
            try:
                return do_attempt(timeout=state.attempt_timeout())
            except Exception as e:
                if not policy.classify(e) or not state.sleep():
                    raise
    """

    def __init__(self, policy: BackoffPolicy, base: float, cap: float,
                 mult: float, deadline: float,
                 clock: Callable[[], float]):
        self.policy = policy
        self._base = base
        self._cap = cap
        self._mult = mult
        self._clock = clock
        self._started = clock()
        self._deadline = (self._started + deadline) if deadline > 0 else None
        self.attempt = 0  # completed (failed) attempts so far
        self.site = policy.label  # overridable per-sequence metrics tag
        self._rng = (random.Random(policy.seed)
                     if policy.seed is not None else _rng)

    def elapsed(self) -> float:
        return self._clock() - self._started

    def remaining(self) -> Optional[float]:
        """Seconds left in the deadline budget; None = unbounded."""
        if self._deadline is None:
            return None
        return self._deadline - self._clock()

    def attempt_timeout(self) -> Optional[float]:
        """Timeout for the NEXT attempt: min(per-attempt cap, remaining
        budget); None = unbounded."""
        rem = self.remaining()
        per = self.policy.attempt_timeout_s
        if per is None:
            return rem
        if rem is None:
            return per
        return min(per, rem)

    def next_delay(self) -> Optional[float]:
        """Delay before the next retry, or None when the budget (deadline
        or max_attempts) is exhausted. Advances the attempt counter."""
        self.attempt += 1
        if (self.policy.max_attempts
                and self.attempt >= self.policy.max_attempts):
            return None
        upper = min(self._cap, self._base * (self._mult ** (self.attempt - 1)))
        delay = (self._rng.uniform(0.0, upper) if self.policy.jitter
                 else upper)
        rem = self.remaining()
        if rem is not None:
            if rem <= 0:
                return None
            delay = min(delay, rem)  # never sleep past the deadline
        _count_retry(self.site or "unlabeled")
        return delay

    def sleep(self, sleep: Callable[[float], None] = time.sleep) -> bool:
        """next_delay() + sleep. False when the budget is exhausted (the
        caller should give up and re-raise)."""
        delay = self.next_delay()
        if delay is None:
            return False
        if delay > 0:
            sleep(delay)
        return True


_rng = random.Random()

_counter_lock = threading.Lock()
_retry_counter = None  # raylint: guarded-by(_counter_lock)


def _count_retry(site: str):
    # Lazy singleton (metrics must not be a hard import here: backoff is
    # used by the wire layer during bootstrap). One counter, tagged by
    # call site, covers every BackoffPolicy loop in the runtime.  Created
    # under _counter_lock: two first-retry threads racing here used to
    # mint two Counters and trip the registry's duplicate check.
    global _retry_counter
    try:
        from ray_tpu.util.metrics import Counter
        with _counter_lock:
            c = _retry_counter
            if c is None:
                c = _retry_counter = Counter(
                    "backoff_retries_total",
                    "retry attempts by call site", tag_keys=("site",))
        c.inc(tags={"site": site})
    except Exception:  # raylint: allow(swallow) metrics must never break a retry loop
        pass


def retry_call(fn: Callable[[Optional[float]], object],
               policy: Optional[BackoffPolicy] = None, *,
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep):
    """Run ``fn(attempt_timeout)`` under ``policy``, retrying retryable
    failures until the budget is spent (the final error re-raises).
    ``fn`` receives the per-attempt timeout (None = unbounded) and may
    ignore it. ``on_retry(attempt, exc)`` fires before each backoff sleep."""
    policy = policy or BackoffPolicy()
    state = policy.start()
    if not state.site:
        state.site = getattr(fn, "__qualname__", "") or "fn"
    while True:
        try:
            return fn(state.attempt_timeout())
        except BaseException as e:  # noqa: BLE001 — classified below
            if not policy.classify(e):
                raise
            if on_retry is not None:
                try:
                    on_retry(state.attempt, e)
                except Exception:  # raylint: allow(swallow) observer hook must not break the retry
                    pass
            if not state.sleep(sleep):
                raise


# -- circuit breaker ----------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-peer fail-fast: CLOSED → (N consecutive failures) → OPEN →
    (reset_s elapses) → HALF_OPEN (one probe) → CLOSED on success, OPEN on
    failure. Thread-safe; all transitions under one lock."""

    def __init__(self, failure_threshold: Optional[int] = None,
                 reset_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._threshold = (failure_threshold if failure_threshold is not None
                           else _config.get("circuit_failure_threshold"))
        self._reset_s = (reset_s if reset_s is not None
                         else _config.get("circuit_reset_s"))
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._state = CLOSED
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def state_code(self) -> int:
        """0=closed 1=half_open 2=open — for metrics gauges."""
        return _STATE_CODE[self.state]

    def _maybe_half_open(self):
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self._reset_s):
            self._state = HALF_OPEN
            self._probing = False

    def allow(self) -> bool:
        """May traffic go to this peer now? In HALF_OPEN exactly one caller
        gets True (the probe) until its outcome is recorded."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._state = CLOSED
            self._probing = False

    def record_failure(self) -> bool:
        """Record one failure; True when this transition OPENED the
        breaker (edge-triggered, for logging/metrics hooks)."""
        with self._lock:
            self._maybe_half_open()
            self._failures += 1
            if self._state == HALF_OPEN:
                # failed probe: straight back to OPEN, restart the clock
                self._state = OPEN
                self._opened_at = self._clock()
                self._probing = False
                return True
            if self._state == CLOSED and self._failures >= self._threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                return True
            return False


class BreakerBoard:
    """Circuit breakers keyed by peer address, created on first use.

    ``on_open(addr)`` fires (outside the board lock) whenever a peer's
    breaker transitions to OPEN — the distributed runtime uses it to mark
    the address suspect for scheduling.
    """

    def __init__(self, failure_threshold: Optional[int] = None,
                 reset_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_open: Optional[Callable[[str], None]] = None):
        self._threshold = failure_threshold
        self._reset_s = reset_s
        self._clock = clock
        self._on_open = on_open
        self._lock = threading.Lock()
        self._breakers = {}  # raylint: guarded-by(self._lock)

    def get(self, addr: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(addr)
            if br is None:
                br = CircuitBreaker(self._threshold, self._reset_s,
                                    self._clock)
                self._breakers[addr] = br
            return br

    def allow(self, addr: str) -> bool:
        return self.get(addr).allow()

    def record_success(self, addr: str):
        self.get(addr).record_success()

    def record_failure(self, addr: str):
        if self.get(addr).record_failure() and self._on_open is not None:
            try:
                self._on_open(addr)
            except Exception:  # raylint: allow(swallow) observer hook must not break failure accounting
                pass

    def drop(self, addr: str):
        with self._lock:
            self._breakers.pop(addr, None)

    def snapshot(self):
        """{addr: state_code} for metrics export."""
        with self._lock:
            items = list(self._breakers.items())
        return {addr: br.state_code() for addr, br in items}
