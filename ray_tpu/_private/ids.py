"""Unique identifiers for jobs, tasks, actors, objects, and nodes.

Capability parity with the reference's ID scheme (``src/ray/common/id.h``):
IDs are fixed-width random byte strings with embedded lineage — an ObjectID
embeds the TaskID that produced it plus a return/put index, and a TaskID
embeds the JobID and (for actor tasks) the ActorID. Unlike the reference we
keep these pure-Python: the control plane here is host-granular (one device
owner process per host) so ID manipulation is never on the hot device path.
"""

from __future__ import annotations

import os
import threading

_UNIQUE_LEN = 16  # bytes of entropy for top-level ids


class BaseID:
    """Immutable, hashable fixed-width id."""

    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes):
            raise TypeError(f"id must be bytes, got {type(id_bytes)}")
        self._bytes = id_bytes
        self._hash = hash((type(self).__name__, id_bytes))

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls.size()))

    @classmethod
    def size(cls) -> int:
        return _UNIQUE_LEN

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls.size())

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.size()

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __reduce__(self):
        # Rebuild through __init__ so _hash is recomputed in the receiving
        # process: Python string hashing is randomized PER PROCESS, and a
        # verbatim-copied _hash (the __slots__ default pickling) makes
        # unpickled ids miss dict lookups against locally-built keys.
        return (type(self), (self._bytes,))

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]})"


class JobID(BaseID):
    @classmethod
    def size(cls) -> int:
        return 4


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class ActorID(BaseID):
    """JobID (4) + unique (12)."""

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(cls.size() - JobID.size()))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.size()])


class TaskID(BaseID):
    """JobID (4) + actor id tail or zeros (4) + unique (8)."""

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        return cls(job_id.binary() + b"\x00" * 4 + os.urandom(8))

    @classmethod
    def for_actor_task(cls, job_id: JobID, actor_id: ActorID) -> "TaskID":
        return cls(job_id.binary() + actor_id.binary()[-4:] + os.urandom(8))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.size()])


class ObjectID(BaseID):
    """TaskID (16) + little-endian index (4).

    Index 0..2^31 are task returns; >=2^31 are ``put`` objects, mirroring the
    reference's return/put index split in ``id.h``.
    """

    PUT_INDEX_BASE = 1 << 31

    @classmethod
    def size(cls) -> int:
        return TaskID.size() + 4

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls.for_return(task_id, cls.PUT_INDEX_BASE + put_index)

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.size()])

    def index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.size():], "little")

    def is_put(self) -> bool:
        return self.index() >= self.PUT_INDEX_BASE

    def is_return(self) -> bool:
        return not self.is_put()


ObjectRefID = ObjectID  # alias


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
