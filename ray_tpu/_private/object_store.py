"""Per-node object store: immutable objects, ref counting, disk spilling.

Capability parity with the reference's plasma store + local object manager
(``src/ray/object_manager/plasma/store.h``,
``src/ray/raylet/local_object_manager.h:99`` SpillObjects), redesigned for a
host-granular TPU runtime:

- **Device objects** (``jax.Array``) are stored *by reference*. JAX arrays are
  immutable by construction, so zero-copy sharing needs no shared-memory
  arena; the value stays resident in HBM (or sharded across the mesh) and the
  store holds only a descriptor. Device objects are never spilled by the byte
  -budget policy (HBM pressure is handled by the training loop via donation /
  rematerialization, not by the store).
- **Host objects** are serialized (immutability) unless they are numpy arrays,
  which are stored as read-only zero-copy views (plasma's zero-copy numpy,
  without the shm arena since workers share the owner process).
- Spilling: when host bytes exceed the configured budget, least-recently-used
  unpinned host objects are pickled to ``object_spilling_dir`` and restored on
  demand (reference behavior: ``local_object_manager.h``).
"""

from __future__ import annotations

import io
import os
import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private.config import _config
from ray_tpu._private.ids import ObjectID


def _is_device_array(value: Any) -> bool:
    try:
        import jax
        return isinstance(value, jax.Array)
    except Exception:
        return False


def _is_numpy(value: Any) -> bool:
    try:
        import numpy as np
        return isinstance(value, np.ndarray)
    except Exception:
        return False


KIND_DEVICE = "device"
KIND_NUMPY = "numpy"
KIND_PICKLED = "pickled"
KIND_ERROR = "error"
KIND_SPILLED = "spilled"


@dataclass
class _Entry:
    kind: str
    data: Any = None
    size_bytes: int = 0
    spill_path: Optional[str] = None
    pin_count: int = 0
    last_access: float = field(default_factory=time.monotonic)
    sealed: threading.Event = field(default_factory=threading.Event)


class ObjectLostError(Exception):
    """Raised when an object was freed/lost and cannot be recovered locally."""


class ObjectStore:
    """One per node. Thread-safe."""

    def __init__(self, node_id=None, capacity_bytes: Optional[int] = None):
        self._node_id = node_id
        self._lock = threading.RLock()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._host_bytes = 0
        self._capacity = capacity_bytes or _config.get("object_store_memory_bytes")
        self._spill_dir = _config.get("object_spilling_dir")
        self._num_spilled = 0
        self._num_restored = 0

    # -- put ------------------------------------------------------------------

    def put(self, object_id: ObjectID, value: Any) -> None:
        """Seal ``value`` under ``object_id``. Values are immutable once sealed."""
        entry = self._build_entry(value)
        with self._lock:
            existing = self._entries.get(object_id)
            if existing is not None and existing.sealed.is_set():
                return  # idempotent re-put (e.g. task retry recomputed the value)
            if existing is not None:
                entry.sealed = existing.sealed
            self._entries[object_id] = entry
            if entry.kind in (KIND_NUMPY, KIND_PICKLED):
                self._host_bytes += entry.size_bytes
            entry.sealed.set()
            self._maybe_spill_locked()

    def put_error(self, object_id: ObjectID, error: BaseException) -> None:
        with self._lock:
            existing = self._entries.get(object_id)
            entry = _Entry(kind=KIND_ERROR, data=error)
            if existing is not None:
                entry.sealed = existing.sealed
            self._entries[object_id] = entry
            entry.sealed.set()

    def create_placeholder(self, object_id: ObjectID) -> None:
        """Register an unsealed entry so getters can block until the value lands."""
        with self._lock:
            if object_id not in self._entries:
                self._entries[object_id] = _Entry(kind=KIND_PICKLED)

    def _build_entry(self, value: Any) -> _Entry:
        if _is_device_array(value):
            # Sharded jax.Array: store the descriptor; bytes live in HBM.
            return _Entry(kind=KIND_DEVICE, data=value, size_bytes=0)
        if isinstance(value, BaseException):
            return _Entry(kind=KIND_ERROR, data=value)
        if _is_numpy(value):
            view = value.view()
            view.flags.writeable = False
            return _Entry(kind=KIND_NUMPY, data=view, size_bytes=view.nbytes)
        buf = io.BytesIO()
        cloudpickle.dump(value, buf, protocol=pickle.HIGHEST_PROTOCOL)
        data = buf.getvalue()
        return _Entry(kind=KIND_PICKLED, data=data, size_bytes=len(data))

    # -- get ------------------------------------------------------------------

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed.is_set()

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """Blocking fetch. Raises the stored exception for error objects."""
        with self._lock:
            entry = self._entries.get(object_id)
        if entry is None:
            raise ObjectLostError(f"{object_id} is not known to this store")
        if not entry.sealed.wait(timeout):
            raise TimeoutError(f"timed out waiting for {object_id}")
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise ObjectLostError(f"{object_id} was freed")
            entry.last_access = time.monotonic()
            if entry.kind == KIND_SPILLED:
                self._restore_locked(object_id, entry)
            if entry.kind == KIND_ERROR:
                raise entry.data
            if entry.kind == KIND_PICKLED:
                return cloudpickle.loads(entry.data)
            return entry.data  # device array or read-only numpy view

    def peek_error(self, object_id: ObjectID) -> Optional[BaseException]:
        """Return the stored exception if this sealed entry is an error object,
        without deserializing value entries (cheap pre-dispatch check)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.sealed.is_set() and e.kind == KIND_ERROR:
                return e.data
            return None

    def wait_ready(self, object_id: ObjectID, timeout: Optional[float]) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
        if entry is None:
            return False
        return entry.sealed.wait(timeout)

    # -- ref counting / free --------------------------------------------------

    def pin(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pin_count += 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pin_count > 0:
                e.pin_count -= 1

    def free(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            if e.kind in (KIND_NUMPY, KIND_PICKLED):
                self._host_bytes -= e.size_bytes
            if e.spill_path and os.path.exists(e.spill_path):
                os.unlink(e.spill_path)

    # -- spilling -------------------------------------------------------------

    def _maybe_spill_locked(self):
        if not _config.get("object_spilling_enabled"):
            return
        threshold = self._capacity * _config.get("object_spilling_threshold")
        if self._host_bytes <= threshold:
            return
        candidates: List[Tuple[float, ObjectID, _Entry]] = [
            (e.last_access, oid, e)
            for oid, e in self._entries.items()
            if e.kind == KIND_PICKLED and e.pin_count == 0 and e.sealed.is_set()
            and e.size_bytes >= _config.get("min_spilling_size_bytes")
        ]
        candidates.sort(key=lambda t: t[0])
        os.makedirs(self._spill_dir, exist_ok=True)
        for _, oid, e in candidates:
            if self._host_bytes <= threshold:
                break
            path = os.path.join(self._spill_dir, oid.hex())
            with open(path, "wb") as f:
                f.write(e.data)
            self._host_bytes -= e.size_bytes
            e.spill_path = path
            e.data = None
            e.kind = KIND_SPILLED
            self._num_spilled += 1

    def _restore_locked(self, object_id: ObjectID, entry: _Entry):
        with open(entry.spill_path, "rb") as f:
            entry.data = f.read()
        os.unlink(entry.spill_path)
        entry.spill_path = None
        entry.kind = KIND_PICKLED
        self._host_bytes += entry.size_bytes
        self._num_restored += 1
        self._maybe_spill_locked()

    # -- introspection --------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "host_bytes": self._host_bytes,
                "capacity_bytes": self._capacity,
                "num_spilled": self._num_spilled,
                "num_restored": self._num_restored,
            }

    def object_ids(self) -> List[ObjectID]:
        with self._lock:
            return list(self._entries.keys())
