"""Per-node object store: immutable objects, ref counting, disk spilling.

Capability parity with the reference's plasma store + local object manager
(``src/ray/object_manager/plasma/store.h``,
``src/ray/raylet/local_object_manager.h:99`` SpillObjects), redesigned for a
host-granular TPU runtime:

- **Device objects** (``jax.Array``) are stored *by reference*. JAX arrays are
  immutable by construction, so zero-copy sharing needs no shared-memory
  arena; the value stays resident in HBM (or sharded across the mesh) and the
  store holds only a descriptor. Device objects are never spilled by the byte
  -budget policy (HBM pressure is handled by the training loop via donation /
  rematerialization, not by the store).
- **Host objects** are serialized (immutability) unless they are numpy arrays,
  which are stored as read-only zero-copy views (plasma's zero-copy numpy,
  without the shm arena since workers share the owner process).
- Spilling: when host bytes exceed the configured budget, least-recently-used
  unpinned host objects are pickled to ``object_spilling_dir`` and restored on
  demand (reference behavior: ``local_object_manager.h``).
"""

from __future__ import annotations
import logging

import io
import os
import pickle
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu import chaos
from ray_tpu._private.config import _config
from ray_tpu._private.framing import loads_framed
from ray_tpu._private.ids import ObjectID

# raylint: hot-path  (payload plane: R8 flags hidden payload copies)
logger = logging.getLogger("ray_tpu")


def _release_native_pin(native, oids: dict, key: bytes):
    """Finalizer for zero-copy framed reads: drop the read pin; if the
    entry was free()d while views kept it pinned, reap the arena slot now
    (Delete refuses pinned objects, so free() could not)."""
    try:
        native.release(key)
        if key not in oids:
            native.delete(key)
    except Exception as e:  # raylint: allow(swallow) interpreter/arena teardown: the pin died with the mapping
        logger.debug("native pin release failed: %s", e)


def _is_device_array(value: Any) -> bool:
    try:
        import jax
        return isinstance(value, jax.Array)
    except Exception:  # raylint: allow(swallow) capability probe: jax optional
        return False


def _is_numpy(value: Any) -> bool:
    try:
        import numpy as np
        return isinstance(value, np.ndarray)
    except Exception:  # raylint: allow(swallow) capability probe: numpy optional
        return False


KIND_DEVICE = "device"
KIND_NUMPY = "numpy"
KIND_PICKLED = "pickled"
KIND_ERROR = "error"
KIND_SPILLED = "spilled"


@dataclass
class _Entry:
    kind: str
    data: Any = None
    size_bytes: int = 0
    spill_path: Optional[str] = None
    pin_count: int = 0
    native: bool = False  # payload lives in the C++ arena, data is None
    framed: bool = False  # payload is an RTF5 frame (remote recv landing)
    last_access: float = field(default_factory=time.monotonic)
    sealed: threading.Event = field(default_factory=threading.Event)


class ObjectLostError(Exception):
    """Raised when an object was freed/lost and cannot be recovered locally."""


class ObjectStore:
    """One per node. Thread-safe."""

    def __init__(self, node_id=None, capacity_bytes: Optional[int] = None):
        self._node_id = node_id
        self._lock = threading.RLock()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._host_bytes = 0
        self._capacity = capacity_bytes or _config.get("object_store_memory_bytes")
        self._spill_dir = _config.get("object_spilling_dir")
        self._num_spilled = 0
        self._num_restored = 0
        # Large pickled payloads live in the C++ mmap arena
        # (``_native/object_store.cc``, the plasma equivalent); the Python
        # dict keeps only descriptors. Heap fallback if g++ is missing.
        self._native = None
        self._native_oids: Dict[bytes, ObjectID] = {}
        # Unsealed remote-receive destinations: oid -> (arena_key|None,
        # size, heap_buf|None). Invisible to readers until sealed.
        self._recv_bufs: Dict[ObjectID, tuple] = {}
        if _config.get("use_native_object_store"):
            try:
                from ray_tpu._native import NativeObjectStore
                if NativeObjectStore.available():
                    self._native = NativeObjectStore(self._capacity)
            except Exception as e:
                logger.warning("native object store unavailable: %s", e)
                self._native = None

    @staticmethod
    def _native_key(object_id: ObjectID) -> bytes:
        import hashlib
        return hashlib.blake2b(object_id.binary(), digest_size=16).digest()

    # -- put ------------------------------------------------------------------

    def put(self, object_id: ObjectID, value: Any) -> None:
        """Seal ``value`` under ``object_id``. Values are immutable once sealed."""
        entry = self._build_entry(value)
        with self._lock:
            existing = self._entries.get(object_id)
            if existing is not None and existing.sealed.is_set():
                return  # idempotent re-put (e.g. task retry recomputed the value)
            if existing is not None:
                entry.sealed = existing.sealed
            self._entries[object_id] = entry
            if entry.kind in (KIND_NUMPY, KIND_PICKLED):
                self._host_bytes += entry.size_bytes
            if (entry.kind == KIND_PICKLED and self._native is not None
                    and entry.size_bytes
                    >= _config.get("native_store_min_object_bytes")):
                self._place_native_locked(object_id, entry)
            entry.sealed.set()
            self._maybe_spill_locked()

    def _place_native_locked(self, object_id: ObjectID, entry: _Entry):
        """Move the pickled payload into the C++ arena, evicting LRU arena
        objects to disk if needed (plasma create + spill backpressure)."""
        key = self._native_key(object_id)
        data = entry.data
        for _ in range(2):
            try:
                if self._native.put(key, data):
                    self._native_oids[key] = object_id
                    entry.data = None
                    entry.native = True
                return
            except MemoryError:
                if not self._evict_native_locked(len(data)):
                    return  # arena can't fit it; keep on heap

    def _evict_native_locked(self, nbytes: int) -> bool:
        """Spill LRU arena objects to disk to free >= nbytes.

        Python-level pins (in-flight task arguments) must stay resident —
        the arena's own pin count only tracks open reads, so filter here.
        No ``min_spilling_size`` filter: this is hard backpressure, where
        freeing anything beats failing the create.
        """
        # Over-ask so pinned candidates can be skipped and still free
        # enough.
        candidates = self._native.evict_candidates(nbytes * 2)
        os.makedirs(self._spill_dir, exist_ok=True)
        oids = self._native_oids
        spilled_any = False
        freed = 0
        for key in candidates:
            if freed >= nbytes and spilled_any:
                break
            oid = oids.get(key)
            e = self._entries.get(oid) if oid is not None else None
            if e is not None and e.pin_count > 0:
                continue  # in use by a dispatched task
            data = self._native.get_bytes(key)
            if e is not None and data is not None:
                path = os.path.join(self._spill_dir, oid.hex())
                with open(path, "wb") as f:
                    f.write(data)
                e.spill_path = path
                e.kind = KIND_SPILLED
                e.native = False
                self._host_bytes -= e.size_bytes
                self._num_spilled += 1
            self._native.delete(key)
            oids.pop(key, None)
            freed += len(data) if data is not None else 0
            spilled_any = True
        return spilled_any

    def put_error(self, object_id: ObjectID, error: BaseException) -> None:
        with self._lock:
            existing = self._entries.get(object_id)
            entry = _Entry(kind=KIND_ERROR, data=error)
            if existing is not None:
                entry.sealed = existing.sealed
                # Replacing a sealed value: release its payload (arena
                # bytes would otherwise leak for the process lifetime).
                if existing.native:
                    key = self._native_key(object_id)
                    self._native.delete(key)
                    self._native_oids.pop(key, None)
                if existing.kind in (KIND_NUMPY, KIND_PICKLED):
                    self._host_bytes -= existing.size_bytes
                if existing.spill_path and os.path.exists(existing.spill_path):
                    os.unlink(existing.spill_path)
            self._entries[object_id] = entry
            entry.sealed.set()

    def create_placeholder(self, object_id: ObjectID) -> None:
        """Register an unsealed entry so getters can block until the value lands."""
        with self._lock:
            if object_id not in self._entries:
                self._entries[object_id] = _Entry(kind=KIND_PICKLED)

    # -- remote receive landing (zero-copy data plane) ------------------------

    def create_recv_buffer(self, object_id: ObjectID,
                           size: int) -> Optional[memoryview]:
        """Writable destination for a remote framed (RTF5) payload: the
        network layer recv_into's chunks DIRECTLY into the object's final
        resting place — an unsealed native arena slot when the arena can
        hold it, else a heap bytearray — so a pull/push lands with zero
        reassembly copies and no re-serialization on ``put``.

        Invisible to readers until :meth:`seal_recv_buffer`; a failed
        transfer calls :meth:`abort_recv_buffer` and leaves no trace.
        Returns None when the object is already sealed locally OR another
        transfer holds a recv buffer for it (aborting under that writer's
        live view would dangle it into reusable arena space)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.sealed.is_set():
                return None
            if object_id in self._recv_bufs:
                return None
            if (self._native is not None
                    and size >= _config.get("native_store_min_object_bytes")):
                key = self._native_key(object_id)
                for _ in range(2):
                    try:
                        view = self._native.create(key, size)
                        if view is None:
                            # stale sealed slot from an aborted ancestor:
                            # replace it (content may differ per attempt)
                            self._native.delete(key)
                            view = self._native.create(key, size)
                        if view is not None:
                            self._recv_bufs[object_id] = (key, size, None)
                            return view
                        break
                    except MemoryError:
                        if not self._evict_native_locked(size):
                            break
            buf = bytearray(size)
            self._recv_bufs[object_id] = (None, size, buf)
            return memoryview(buf)

    def seal_recv_buffer(self, object_id: ObjectID) -> None:
        """Publish a fully-received framed payload as a sealed entry.
        ``get()`` decodes it lazily — zero-copy views straight out of the
        arena pages (or the heap buffer) with no intermediate pickle."""
        with self._lock:
            rec = self._recv_bufs.pop(object_id, None)
            if rec is None:
                return
            key, size, heap = rec
            existing = self._entries.get(object_id)
            if existing is not None and existing.sealed.is_set():
                if key is not None:  # raced a local put: ours is redundant
                    self._native.seal(key)
                    self._native.delete(key)
                return
            entry = _Entry(kind=KIND_PICKLED, size_bytes=size, framed=True)
            if key is not None:
                self._native.seal(key)
                self._native_oids[key] = object_id
                entry.native = True
            else:
                entry.data = heap
            if existing is not None:
                entry.sealed = existing.sealed
            self._entries[object_id] = entry
            self._host_bytes += size
            entry.sealed.set()
            self._maybe_spill_locked()

    def abort_recv_buffer(self, object_id: ObjectID) -> None:
        """Discard a half-landed transfer (sender died / fetch failed).
        The slot was never sealed, so no reader ever observed it."""
        with self._lock:
            self._abort_recv_locked(object_id)

    def _abort_recv_locked(self, object_id: ObjectID) -> None:
        rec = self._recv_bufs.pop(object_id, None)
        if rec is None or rec[0] is None:
            return
        key = rec[0]
        try:
            # Delete refuses unsealed slots (create-pin); seal first.
            self._native.seal(key)
            self._native.delete(key)
        except Exception as e:  # raylint: allow(swallow) abort is best-effort; an orphan slot is LRU-evictable once sealed
            logger.debug("recv-buffer abort failed: %s", e)

    def _build_entry(self, value: Any) -> _Entry:
        if _is_device_array(value):
            # Sharded jax.Array: store the descriptor; bytes live in HBM.
            return _Entry(kind=KIND_DEVICE, data=value, size_bytes=0)
        if isinstance(value, BaseException):
            return _Entry(kind=KIND_ERROR, data=value)
        if _is_numpy(value):
            view = value.view()
            view.flags.writeable = False
            return _Entry(kind=KIND_NUMPY, data=view, size_bytes=view.nbytes)
        buf = io.BytesIO()
        cloudpickle.dump(value, buf, protocol=pickle.HIGHEST_PROTOCOL)
        data = buf.getvalue()
        return _Entry(kind=KIND_PICKLED, data=data, size_bytes=len(data))

    # -- get ------------------------------------------------------------------

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed.is_set()

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """Blocking fetch. Raises the stored exception for error objects."""
        if chaos.ENABLED and chaos.inject(
                "object.store.get", object=object_id.hex()[:8]) == "drop":
            # simulate local loss (eviction race): callers fall back to
            # remote fetch / lineage reconstruction
            raise ObjectLostError(f"{object_id} dropped by chaos schedule")
        with self._lock:
            entry = self._entries.get(object_id)
        if entry is None:
            raise ObjectLostError(f"{object_id} is not known to this store")
        if not entry.sealed.wait(timeout):
            raise TimeoutError(f"timed out waiting for {object_id}")
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise ObjectLostError(f"{object_id} was freed")
            entry.last_access = time.monotonic()
            if entry.kind == KIND_SPILLED:
                self._restore_locked(object_id, entry)
            if entry.kind == KIND_ERROR:
                raise entry.data
            if entry.kind == KIND_PICKLED:
                if entry.native:
                    key = self._native_key(object_id)
                    view = self._native.get(key)
                    if view is None:
                        raise ObjectLostError(f"{object_id} lost from arena")
                    if entry.framed:
                        # Framed (RTF5) payload: arrays decode as views into
                        # the arena pages — keep the slot pinned until the
                        # last such view dies.
                        value, zero_copy = loads_framed(view)
                        if zero_copy:
                            try:
                                weakref.finalize(view.obj, _release_native_pin,
                                                 self._native, self._native_oids,
                                                 key)
                            except TypeError:
                                pass  # unfinalizable backing: stay pinned
                        else:
                            view.release()
                            self._native.release(key)
                        return value
                    # Plain pickle: loads copies what it keeps.
                    try:
                        return cloudpickle.loads(view)
                    finally:
                        view.release()
                        self._native.release(key)
                if entry.framed:
                    value, _ = loads_framed(entry.data)
                    return value
                return cloudpickle.loads(entry.data)
            return entry.data  # device array or read-only numpy view

    def peek_error(self, object_id: ObjectID) -> Optional[BaseException]:
        """Return the stored exception if this sealed entry is an error object,
        without deserializing value entries (cheap pre-dispatch check)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.sealed.is_set() and e.kind == KIND_ERROR:
                return e.data
            return None

    def wait_ready(self, object_id: ObjectID, timeout: Optional[float]) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
        if entry is None:
            return False
        return entry.sealed.wait(timeout)

    # -- ref counting / free --------------------------------------------------

    def pin(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pin_count += 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pin_count > 0:
                e.pin_count -= 1

    def free(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            if e.kind in (KIND_NUMPY, KIND_PICKLED):
                self._host_bytes -= e.size_bytes
            if e.native:
                key = self._native_key(object_id)
                self._native.delete(key)
                self._native_oids.pop(key, None)
            if e.spill_path and os.path.exists(e.spill_path):
                os.unlink(e.spill_path)

    # -- spilling -------------------------------------------------------------

    def _maybe_spill_locked(self):
        if not _config.get("object_spilling_enabled"):
            return
        threshold = self._capacity * _config.get("object_spilling_threshold")
        if self._host_bytes <= threshold:
            return
        candidates: List[Tuple[float, ObjectID, _Entry]] = [
            (e.last_access, oid, e)
            for oid, e in self._entries.items()
            if e.kind == KIND_PICKLED and e.pin_count == 0 and e.sealed.is_set()
            and e.size_bytes >= _config.get("min_spilling_size_bytes")
        ]
        candidates.sort(key=lambda t: t[0])
        os.makedirs(self._spill_dir, exist_ok=True)
        for _, oid, e in candidates:
            if self._host_bytes <= threshold:
                break
            if e.native:
                key = self._native_key(oid)
                data = self._native.get_bytes(key)
                self._native.delete(key)
                self._native_oids.pop(key, None)
                e.native = False
            else:
                data = e.data
            path = os.path.join(self._spill_dir, oid.hex())
            with open(path, "wb") as f:
                f.write(data)
            self._host_bytes -= e.size_bytes
            e.spill_path = path
            e.data = None
            e.kind = KIND_SPILLED
            self._num_spilled += 1

    def _restore_locked(self, object_id: ObjectID, entry: _Entry):
        with open(entry.spill_path, "rb") as f:
            entry.data = f.read()
        os.unlink(entry.spill_path)
        entry.spill_path = None
        entry.kind = KIND_PICKLED
        self._host_bytes += entry.size_bytes
        self._num_restored += 1
        self._maybe_spill_locked()

    # -- introspection --------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "num_objects": len(self._entries),
                "host_bytes": self._host_bytes,
                "capacity_bytes": self._capacity,
                "num_spilled": self._num_spilled,
                "num_restored": self._num_restored,
                "native_arena": self._native is not None,
            }
            if self._native is not None:
                used, cap, count = self._native.stats()
                out["native_used_bytes"] = used
                out["native_num_objects"] = count
            return out

    def object_ids(self) -> List[ObjectID]:
        with self._lock:
            return list(self._entries.keys())
