"""Runtime configuration registry.

Parity with the reference's ``RAY_CONFIG(type, name, default)`` flag table
(``src/ray/common/ray_config_def.h:22ff``): every flag is declared once with a
type and default, is overridable via a ``RAY_TPU_<NAME>`` environment variable,
and may be overridden programmatically via ``ray_tpu.init(_system_config=...)``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


class Config:
    """Typed, env-overridable flag registry (singleton at ``ray_tpu._config``)."""

    def __init__(self):
        self._defs: Dict[str, tuple] = {}
        self._values: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def define(self, name: str, typ: type, default: Any, doc: str = ""):
        self._defs[name] = (typ, default, doc)
        env = os.environ.get(f"RAY_TPU_{name.upper()}")
        if env is not None:
            self._values[name] = _PARSERS[typ](env)

    def get(self, name: str) -> Any:
        if name in self._values:
            return self._values[name]
        return self._defs[name][1]

    def set(self, name: str, value: Any):
        with self._lock:
            typ = self._defs[name][0]
            if not isinstance(value, typ):
                value = _PARSERS[typ](str(value))
            self._values[name] = value

    def apply_system_config(self, system_config: Dict[str, Any] | str | None):
        if system_config is None:
            return
        if isinstance(system_config, str):
            system_config = json.loads(system_config)
        for k, v in system_config.items():
            self.set(k, v)

    def to_dict(self) -> Dict[str, Any]:
        return {name: self.get(name) for name in self._defs}

    def __getattr__(self, name):
        defs = object.__getattribute__(self, "_defs")
        if name in defs:
            return self.get(name)
        raise AttributeError(name)


_config = Config()

# -- Core scheduling / execution ------------------------------------------------
_config.define("num_workers_soft_limit", int, 0,
               "0 = num_cpus; max concurrently executing CPU-bound tasks per node")
_config.define("task_retry_delay_ms", int, 10, "delay before resubmitting a retryable task")
_config.define("actor_restart_delay_ms", int, 10, "delay before restarting a failed actor")
_config.define("worker_lease_timeout_s", float, 30.0, "max wait for resources before spillback")
_config.define("scheduler_spread_threshold", float, 0.5,
               "utilization threshold for hybrid pack->spread switch (reference: "
               "ray_config_def.h scheduler_spread_threshold)")
_config.define("scheduler_top_k_fraction", float, 0.2,
               "fraction of nodes in the hybrid policy random top-k pick")
_config.define("max_pending_lease_requests_per_scheduling_category", int, 10, "")
_config.define("use_native_scheduler", bool, True,
               "hybrid/spread policy selection via the C++ kernels "
               "(ray_tpu/_native/scheduling.cc); Python fallback otherwise")

# -- Object store ---------------------------------------------------------------
_config.define("object_store_memory_bytes", int, 2 << 30,
               "per-node budget for host objects before spilling")
_config.define("object_spilling_enabled", bool, True, "spill to disk when over budget")
_config.define("object_spilling_dir", str, "/tmp/ray_tpu_spill", "")
_config.define("object_spilling_threshold", float, 0.8, "fraction of budget that triggers spill")
_config.define("min_spilling_size_bytes", int, 1 << 20, "batch small objects up to this size")
_config.define("inline_object_max_bytes", int, 100 * 1024,
               "small objects returned inline instead of via the store")
_config.define("use_native_object_store", bool, True,
               "keep pickled host objects in the C++ mmap arena "
               "(ray_tpu/_native/object_store.cc); falls back to heap "
               "bytes when the toolchain is unavailable")
_config.define("native_store_min_object_bytes", int, 4096,
               "objects smaller than this stay on the Python heap (arena "
               "round-trip overhead dominates below it)")

# -- Failure detection ----------------------------------------------------------
_config.define("heartbeat_interval_ms", int, 100, "node heartbeat period")
_config.define("num_heartbeats_timeout", int, 30, "missed heartbeats before a node is dead")
_config.define("health_check_period_ms", int, 1000, "actor health check period")

# -- RPC / retry policy ---------------------------------------------------------
# The shared backoff policy (_private/backoff.py): exponential backoff with
# full jitter, bounded by an overall deadline budget. Every retry loop in the
# runtime resolves its pacing from these four knobs unless it overrides them.
_config.define("rpc_connect_timeout_s", float, 10.0,
               "TCP connect timeout for RpcClient dials")
_config.define("rpc_call_deadline_s", float, 0.0,
               "default per-call reply deadline when call() passes no "
               "timeout; 0 disables (task-push replies legitimately take "
               "as long as the task runs)")
_config.define("backoff_base_ms", int, 50, "first retry delay upper bound")
_config.define("backoff_max_ms", int, 5000, "retry delay cap")
_config.define("backoff_multiplier", float, 2.0, "delay growth per attempt")
_config.define("backoff_deadline_s", float, 30.0,
               "default overall retry budget; retries stop when spent")
_config.define("state_reconnect_deadline_s", float, 15.0,
               "StateClient redial budget across a state-service restart")
_config.define("task_retry_max_delay_ms", int, 2000,
               "cap on the jittered exponential resubmission delay "
               "(base is task_retry_delay_ms)")
_config.define("circuit_failure_threshold", int, 3,
               "consecutive failures before a peer's circuit breaker opens")
_config.define("circuit_reset_s", float, 5.0,
               "open-breaker hold time before the half-open probe")

_config.define("daemon_admission_queue_limit", int, 1000,
               "pending tasks a daemon accepts before spilling back "
               "(backpressure: one daemon must not absorb the cluster)")
_config.define("task_push_batching", bool, True,
               "coalesce task pushes into one TaskBatchMsg frame per "
               "daemon (fewer syscalls/reader wakeups on both sides); "
               "the linger flusher (task_push_flush_ms) bounds the "
               "latency a lone task waits for the frame to fill")
_config.define("task_push_flush_ms", float, 0.25,
               "max linger before a queued task-push batch is shipped; "
               "<= 0 flushes synchronously at every dispatch (one frame "
               "per pass, the pre-linger behavior)")
_config.define("inline_dispatch", bool, False,
               "dispatch ref-free tasks inline on the submitting thread "
               "when the dispatcher is idle; wins on many-core hosts "
               "(skips two context switches), loses on saturated ones "
               "(defeats the dispatcher's batched passes)")

# -- Data plane (bulk object transfer) -------------------------------------------
_config.define("data_streams_per_peer", int, -1,
               "extra raw data connections per peer for chunked bulk "
               "transfers; multi-GB fetches stripe across them instead of "
               "head-of-line-blocking the multiplexed control socket. "
               ">0 explicit, 0 disables the pool (chunks ride the control "
               "connection), <0 auto (transport bandwidth probe)")
_config.define("fetch_chunk_bytes", int, 0,
               "chunk size for FETCH_OBJECT/PUSH_OBJECT/checkpoint-chunk "
               "streaming; 0 auto-tunes from the transport bandwidth probe "
               "(falls back to 8 MiB with the probe disabled)")
_config.define("data_socket_buffer_bytes", int, 0,
               "SO_SNDBUF/SO_RCVBUF for data-plane sockets; 0 auto-sizes "
               "from the transport probe (else to one fetch chunk; the "
               "kernel caps silently at net.core.[rw]mem_max)")
_config.define("transport_probe_bytes", int, 8 * 1024 * 1024,
               "bytes the one-shot loopback bandwidth probe streams per "
               "candidate chunk size to auto-tune fetch_chunk_bytes, "
               "stream count and socket buffers; 0 disables the probe "
               "(static defaults apply)")

# -- Control plane batching ------------------------------------------------------
_config.define("state_batch_max", int, 64,
               "object-directory ops coalesced into one state-service "
               "write burst before an immediate flush")
_config.define("state_batch_flush_ms", float, 2.0,
               "max latency an enqueued directory op waits for batching; "
               "<= 0 disables batching (every op is a synchronous RPC)")

# -- Checkpoint engine ----------------------------------------------------------
_config.define("checkpoint_queue_depth", int, 2,
               "pending async saves per checkpoint engine before save() "
               "blocks (backpressure instead of unbounded host-copy "
               "buffering)")
_config.define("checkpoint_io_workers", int, 4,
               "hash/write worker threads per checkpoint engine: sha256 "
               "chunking overlaps chunk-file writes per leaf (both release "
               "the GIL), and restore reads chunks concurrently; <=1 "
               "degrades to the serial path")
_config.define("checkpoint_hash_verify", bool, True,
               "re-hash every chunk on restore and fail loudly on mismatch")
_config.define("checkpoint_shard_wait_s", float, 60.0,
               "how long the rank-0 committer waits for the other ranks' "
               "shard indexes before abandoning a save")
_config.define("checkpoint_final_timeout_s", float, 10.0,
               "per-worker deadline when collecting final checkpoints at "
               "trainer shutdown; a dead worker forfeits its slot")
_config.define("checkpoint_gc_grace_s", float, 300.0,
               "gc leaves unreferenced chunk/tmp files younger than this "
               "alone: peer ranks on the same root write chunks before "
               "their shard index lands, and a tmp file may be one "
               "os.replace away from becoming a live chunk")

# -- Host-shared object plane ---------------------------------------------------
_config.define("arena_enabled", bool, True,
               "share one shm arena per host between daemons (fd-passing)")
_config.define("arena_capacity_mb", int, 256, "host arena size")
_config.define("object_push_threshold_bytes", int, 256 * 1024,
               "proactively push task args at least this large to the "
               "executing daemon (push_manager.h role)")
_config.define("object_push_window_bytes", int, 32 * 1024 * 1024,
               "per-peer in-flight push budget (backpressure window)")

# -- Collectives / device plane -------------------------------------------------
_config.define("collective_default_backend", str, "xla", "xla | cpu")
_config.define("collective_compression", str, "none",
               "default wire compression for collective groups created "
               "without an explicit CollectiveConfig: none | q8 (block-wise "
               "symmetric int8) | fp8 (float8_e4m3fn blocks); allreduce/"
               "reducescatter payloads ship compressed with per-block absmax "
               "scales, dequantized into a full-precision accumulate")
_config.define("quant_block_bytes", int, 256,
               "input bytes per quantization scale block; one f32 scale "
               "rides each block, so 256 ships f32 tensors at ~0.27x wire")
_config.define("ici_axes_preference", str, "data,fsdp,tensor",
               "mesh axis order preference: fastest-varying axes ride ICI")

# -- Logging / events -----------------------------------------------------------
_config.define("event_log_dir", str, "/tmp/ray_tpu/events", "")
_config.define("event_log_enabled", bool, False,
               "persist structured events as JSONL under event_log_dir")
_config.define("log_dir", str, "/tmp/ray_tpu/logs", "")
_config.define("metrics_report_interval_ms", int, 2000, "")

# -- Tracing --------------------------------------------------------------------
_config.define("tracing_enabled", bool, False, "emit per-task spans")
_config.define("profiling_enabled", bool, True, "record timeline events")
_config.define("trace_ring_size", int, 200_000,
               "per-process span ring capacity; oldest spans drop when full "
               "(drops exported as the profiler_spans_dropped counter)")

# -- Flight recorder (post-mortem forensics) -------------------------------------
_config.define("flight_recorder_enabled", bool, True,
               "spool spans/logs/metrics to a crash-safe on-disk ring so a "
               "SIGKILL'd process still leaves evidence behind")
_config.define("flight_recorder_dir", str, "/tmp/ray_tpu/flight",
               "root for per-process recording dirs and sealed crash bundles")
_config.define("flight_recorder_spool_ms", int, 500,
               "spool-thread tick period; lower = fresher last words after "
               "a hard kill, higher = cheaper")
_config.define("flight_recorder_segment_bytes", int, 4 << 20,
               "spool segment rotation threshold; two segments are kept, so "
               "on-disk spool per process is bounded at ~2x this")
_config.define("flight_recorder_tail_events", int, 256,
               "ring size for the span/log/chaos tails carried per spool "
               "record and into a sealed bundle")
_config.define("flight_recorder_retention_s", int, 3600,
               "dead recordings (clean exits and sealed bundles) older than "
               "this are pruned at the next recorder install")

# -- Node lifecycle / graceful drain --------------------------------------------
_config.define("drain_deadline_s", float, 30.0,
               "default drain budget when none is supplied: in-flight work "
               "gets this long to finish before the node decommissions")
_config.define("drain_poll_ms", int, 50,
               "drain orchestrator poll period while waiting for in-flight "
               "tasks to quiesce")
_config.define("drain_checkpoint_root", str, "/tmp/ray_tpu_drain",
               "shared directory for drained-actor snapshots; must be "
               "reachable from the surviving nodes (NFS on real fleets)")
_config.define("preempt_probe_url", str, "",
               "GCE-metadata-style preemption probe URL polled by the host "
               "daemon; a 200 response with a body other than NONE/FALSE "
               "triggers a self-drain. Empty disables the probe.")
_config.define("preempt_lead_s", float, 10.0,
               "drain budget requested when the preemption watcher fires "
               "(eviction lead time promised by the provider)")
_config.define("preempt_poll_ms", int, 500,
               "preemption watcher poll period in the host daemon")
_config.define("preempt_probe_failure_threshold", int, 3,
               "consecutive preempt_probe_url failures before the doctor "
               "flags the node's watcher as blind (the daemon also "
               "exports the count as the preempt_probe_failures gauge)")

# -- Preemption-hazard estimator (autoscaler/hazard.py) ---------------------------
_config.define("hazard_window_s", float, 3600.0,
               "sliding window over journaled preemption-notice events; "
               "events older than this stop contributing to hazard and "
               "are garbage-collected from the state KV")
_config.define("hazard_halflife_s", float, 900.0,
               "exponential-decay half-life for event contributions "
               "inside the window: a notice this old counts half as much "
               "as one that just landed")
_config.define("hazard_probe_weight", float, 2.0,
               "per-node hazard added per consecutive preempt-probe "
               "failure (an unreachable metadata endpoint means the real "
               "notice may never be seen, so the node reads as riskier)")
_config.define("hazard_drain_threshold", float, 6.0,
               "per-node hazard score (decayed preemptions/hour) above "
               "which the autoscaler proactively drains the highest-"
               "hazard node with the full drain_deadline_s budget")
_config.define("hazard_placement_threshold", float, 2.0,
               "hazard score above which a node is hinted pending-drain: "
               "the schedulers treat it as a last-choice placement")
_config.define("hazard_proactive_drains", bool, True,
               "let the autoscaler start proactive drains when hazard "
               "crosses hazard_drain_threshold (off = estimate and hint "
               "placements only)")
_config.define("hazard_rate_floor_per_hour", float, 0.0,
               "assumed fleet preemption rate when no events have been "
               "journaled yet (the cadence solver's cold-start prior; "
               "set to the provider's advertised preemption rate)")

# -- Adaptive checkpoint cadence (checkpoint/cadence.py) --------------------------
_config.define("checkpoint_cadence_min_steps", int, 1,
               "floor for checkpoint_frequency='auto': never checkpoint "
               "more often than every report")
_config.define("checkpoint_cadence_max_steps", int, 200,
               "ceiling for checkpoint_frequency='auto': checkpoint at "
               "least this often even when hazard reads zero")
_config.define("checkpoint_cadence_refresh_steps", int, 10,
               "reports between cadence re-solves: each refresh re-reads "
               "the fleet hazard rate and the measured step/checkpoint "
               "costs, so cadence tracks a hazard change mid-run")

# -- Performance plane (streaming histograms + sampling profiler) ---------------
_config.define("perf_enabled", bool, True,
               "continuous performance plane: streaming log-scale latency "
               "histograms on every hot path (rpc/task/fetch/checkpoint/"
               "serve/drain) plus the periodic stack sampler")
_config.define("perf_hist_buckets", int, 64,
               "bucket count per latency histogram; bounds are geometric "
               "from 1us to 60s, so more buckets = tighter quantile error")
_config.define("perf_sampler_hz", float, 19.0,
               "stack-sampler frequency per process; 0 disables the sampler "
               "while leaving the histograms on")
_config.define("perf_top_interval_s", float, 2.0,
               "`ray-tpu top` refresh period between head polls")
_config.define("goodput_enabled", bool, True,
               "goodput ledger: per-job wall-clock attribution into exclusive "
               "categories (compute/data_wait/collective_wait/ckpt_stall/"
               "compile/restart_downtime/idle), federated at /api/goodput")
_config.define("comms_enabled", bool, True,
               "communication observability plane: per-op collective ledger "
               "(bytes/duration/algbw/busbw), rendezvous arrival-skew "
               "attribution, runtime collective-fingerprint divergence "
               "check, and the StripedTransfer peer link matrix, federated "
               "at /api/comms")
_config.define("clock_sync_enabled", bool, True,
               "estimate a per-daemon clock offset against the state service "
               "from register/heartbeat request-reply midpoints and use it to "
               "de-skew cross-host task.e2e latencies")
_config.define("serve_ingress_put_threshold_bytes", int, 256 * 1024,
               "serve ingress bodies at least this large are put() into the "
               "object plane and handed to the replica as a ref, so the "
               "bytes ride the striped transport pool instead of pickle")

# -- Interactive serving (continuous batching / routing / SLO autoscaling) -------
_config.define("serve_target_latency_ms", float, 100.0,
               "default per-request latency budget for a deployment when "
               "DeploymentConfig.target_latency_ms is 0: the replica "
               "micro-batcher sizes batches to fit it, the router sheds "
               "(503) when every replica's queue estimate exceeds it, and "
               "the SLO autoscaler holds the federated p95 under it")
_config.define("serve_queue_deadline_ms", float, 2000.0,
               "max age of a request in a replica's admission queue (and "
               "the router's default replica-wait) before it is shed with "
               "ServeOverloadedError instead of serving a stale response; "
               "<= 0 disables shedding (requests wait indefinitely)")
_config.define("serve_batch_retry_singletons", bool, True,
               "when a serve batch function raises, re-run each member as "
               "a singleton once so one poisoned request fails alone "
               "instead of taking its batchmates down; off = every member "
               "gets the batch-level BatchExecutionError")
_config.define("serve_autoscale_ewma_alpha", float, 0.3,
               "EWMA weight for the SLO autoscaler's federated queue-wait "
               "p95 sensor: higher reacts faster to latency spikes, lower "
               "rides through transients without scaling")

# -- Autopilot (closed-loop cluster retuning, ray_tpu/autopilot/) ----------------
_config.define("autopilot_enabled", bool, False,
               "host the autopilot controller in the dashboard head: every "
               "tick it reads the merged perf/goodput/comms planes and "
               "retunes the autopilot-owned knobs through the guardrailed "
               "actuator layer, journaling every decision to the state KV "
               "('autopilot' namespace) for ray_tpu.doctor --explain")
_config.define("autopilot_tick_s", float, 5.0,
               "controller tick period; poke() wakes it early when a plane "
               "merge sees something worth reacting to")
_config.define("autopilot_watch_ticks", int, 3,
               "ticks each actuated knob stays under its post-change SLO "
               "watch before the change is considered kept")
_config.define("autopilot_revert_pct", float, 5.0,
               "SLO regression tolerance during the watch window: the knob "
               "auto-reverts (journaled) when the guarded metric moves "
               "worse than this percentage from its pre-change baseline")
_config.define("autopilot_decision_ttl_s", float, 600.0,
               "seconds a journaled decision claims its knob; an expired "
               "claim retires quietly so the policy can re-examine the "
               "knob against fresh telemetry")
_config.define("autopilot_flap_window_s", float, 600.0,
               "oscillation guard window: a knob actuated >= 3 times "
               "inside it is frozen for the remainder (and flagged by "
               "the doctor)")
_config.define("autopilot_max_changes_per_tick", int, 2,
               "actuation budget per controller tick: bounds the blast "
               "radius of any single snapshot's worth of proposals")
_config.define("autopilot_rel_err_budget", float, 5e-3,
               "relative-error budget the collective policy may spend on "
               "wire compression: only schemes whose measured block-quant "
               "error fits are ever proposed (q8 ~ 1.5e-3, fp8 ~ 1.2e-2)")
_config.define("autopilot_busbw_floor_gbps", float, 4.0,
               "busbw floor below which the collective policy treats a "
               "reduction as link-bound and escalates the wire scheme "
               "(then the two-level hierarchy)")

# -- Autopilot-owned actuation targets -------------------------------------------
_config.define("collective_ranks_per_host", int, 0,
               "default CollectiveConfig.ranks_per_host for groups created "
               "without an explicit config: >1 decomposes allreduce into "
               "intra-host reduce + inter-host exchange + intra-host "
               "broadcast; 0/1 single-level (autopilot-owned)")
_config.define("data_prefetch_batches", int, 0,
               "default prefetch depth for Dataset.iter_batches when the "
               "caller passes prefetch_batches=0: batches assembled ahead "
               "on a background thread (autopilot-owned; retuned from the "
               "goodput ledger's data_wait attribution)")
_config.define("checkpoint_cadence_autopilot_steps", int, 0,
               "cluster-level checkpoint cadence override solved by the "
               "autopilot's hazard loop; >0 wins over the local "
               "CadenceController solve (still clamped to the cadence "
               "min/max bounds), 0 defers to local control")
