"""In-process log ring buffer.

The per-daemon half of the dashboard's log viewer (reference:
``dashboard/modules/log/log_agent.py:1`` tails worker log FILES; this
runtime's workers are threads of one daemon process, so the daemon keeps
its own recent log lines in memory and serves them over the NODE_DEBUG
RPC — no log-directory contract needed).
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import List, Optional

_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


class RingLogHandler(logging.Handler):
    """Keeps the last ``capacity`` formatted log lines."""

    def __init__(self, capacity: int = 2000):
        super().__init__()
        self.setFormatter(logging.Formatter(_FMT))
        self._lock2 = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)

    def emit(self, record: logging.LogRecord):
        try:
            line = self.format(record)
        except Exception:  # noqa: BLE001  # raylint: allow(swallow) cannot log from inside the log handler
            return
        with self._lock2:
            self._ring.append(line)

    def tail(self, n: int) -> List[str]:
        with self._lock2:
            items = list(self._ring)
        return items[-n:] if n > 0 else []


_handler: Optional[RingLogHandler] = None
_install_lock = threading.Lock()


def install(capacity: int = 2000) -> RingLogHandler:
    """Attach the ring to the root logger (idempotent)."""
    global _handler
    with _install_lock:
        if _handler is None:
            _handler = RingLogHandler(capacity)
            logging.getLogger().addHandler(_handler)
        return _handler


def tail(n: int) -> List[str]:
    return _handler.tail(n) if _handler is not None else []
