"""In-process log ring buffer.

The per-daemon half of the dashboard's log viewer (reference:
``dashboard/modules/log/log_agent.py:1`` tails worker log FILES; this
runtime's workers are threads of one daemon process, so the daemon keeps
its own recent log lines in memory and serves them over the NODE_DEBUG
RPC — no log-directory contract needed).

Each stored line carries the trace id that was active when it was
emitted (empty when tracing is off), so a NODE_DEBUG tail can be
filtered down to the log lines of ONE distributed trace.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import List, Optional

_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def _active_trace_id() -> str:
    # Lazy import: log_ring installs very early; observability's one-bool
    # fast path keeps this near-free when tracing is off.
    try:
        from ray_tpu import observability
        return observability.current_trace_id()
    except Exception:  # raylint: allow(swallow) cannot log from inside the log handler
        return ""


class RingLogHandler(logging.Handler):
    """Keeps the last ``capacity`` formatted log lines as
    ``(line, trace_id)`` pairs."""

    def __init__(self, capacity: int = 2000):
        super().__init__()
        self.setFormatter(logging.Formatter(_FMT))
        self._lock2 = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        # Monotonic append counter for incremental readers (the flight
        # recorder spools only lines it has not shipped yet).
        self._seq = 0

    def emit(self, record: logging.LogRecord):
        try:
            line = self.format(record)
            tid = _active_trace_id()
            if tid:
                line = f"{line} trace_id={tid}"
        except Exception:  # noqa: BLE001  # raylint: allow(swallow) cannot log from inside the log handler
            return
        with self._lock2:
            self._ring.append((line, tid))
            self._seq += 1

    def tail_since(self, cursor: int) -> "tuple[int, List[str]]":
        """Incremental tail: lines appended after ``cursor`` (previously
        returned by this method; start at 0). Lines that fell off the ring
        between reads are lost; returns ``(new_cursor, lines)``."""
        with self._lock2:
            new = self._seq - cursor
            if new <= 0:
                return self._seq, []
            if new > len(self._ring):
                new = len(self._ring)
            items = list(self._ring)[-new:] if new else []
            return self._seq, [it[0] for it in items]

    def tail(self, n: int, trace_id: str = "") -> List[str]:
        with self._lock2:
            items = list(self._ring)
        if trace_id:
            items = [it for it in items if it[1] == trace_id]
        lines = [it[0] for it in items]
        return lines[-n:] if n > 0 else []


_handler: Optional[RingLogHandler] = None
_install_lock = threading.Lock()


def install(capacity: int = 2000) -> RingLogHandler:
    """Attach the ring to the root logger (idempotent)."""
    global _handler
    with _install_lock:
        if _handler is None:
            _handler = RingLogHandler(capacity)  # raylint: allow(data-race) emit-path readers take a GIL-atomic snapshot; install is idempotent under _install_lock
            logging.getLogger().addHandler(_handler)
        return _handler


def tail(n: int, trace_id: str = "") -> List[str]:
    return _handler.tail(n, trace_id=trace_id) if _handler is not None else []


def tail_since(cursor: int) -> "tuple[int, List[str]]":
    if _handler is None:
        return cursor, []
    return _handler.tail_since(cursor)
