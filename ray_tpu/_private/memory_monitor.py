"""Host-memory OOM guard.

Parity with ``src/ray/common/memory_monitor.h:32`` (the raylet's
periodic usage monitor that triggers worker-killing above a usage
threshold), redesigned for the thread-worker daemon: there are no child
worker processes to kill, so the guard acts at ADMISSION — a daemon
whose host is above the memory-usage threshold spills pushed tasks back
to the caller, which re-routes them to a node that still has headroom
(and if none has, the caller's retry grace surfaces the pressure as a
scheduling error instead of the host OOM-killing the device owner).

Sampling reads ``/proc/meminfo`` (cgroup v2 limits honored when
``memory.max``/``memory.current`` are present — daemons routinely run
inside containers whose limit is far below the host's) plus this
process's RSS. Everything is configurable:

- ``memory_usage_threshold`` (default 0.95, fraction of usable memory)
- ``memory_monitor_refresh_ms`` (default 250; <= 0 disables the monitor)
"""

from __future__ import annotations
import logging

import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu._private.config import _config

logger = logging.getLogger("ray_tpu")


def _read_meminfo_kb() -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, v = line.split(":", 1)
                if k in ("MemTotal", "MemAvailable"):
                    out[k] = int(v.split()[0])
    except OSError:
        pass
    return out


def _read_cgroup_limit_bytes() -> Optional[int]:
    """cgroup v2 memory.max ("max" = unlimited), else None."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        return None if raw == "max" else int(raw)
    except (OSError, ValueError):
        return None


def _read_cgroup_current_bytes() -> Optional[int]:
    """Working-set usage: memory.current MINUS inactive_file. Raw
    memory.current counts reclaimable page cache, which would latch the
    guard permanently on any file-streaming workload; the reference
    monitor subtracts inactive_file for exactly this reason
    (``memory_monitor.cc`` GetCGroupMemoryUsedBytes)."""
    try:
        with open("/sys/fs/cgroup/memory.current") as f:
            current = int(f.read().strip())
    except (OSError, ValueError):
        return None
    inactive_file = 0
    try:
        with open("/sys/fs/cgroup/memory.stat") as f:
            for line in f:
                if line.startswith("inactive_file "):
                    inactive_file = int(line.split()[1])
                    break
    except (OSError, ValueError):
        pass
    return max(0, current - inactive_file)


def _read_self_rss_kb() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


class MemoryMonitor:
    """Periodic host/cgroup memory sampler with an over-threshold latch.

    ``usage_reader`` is injectable for tests: a callable returning
    ``(used_bytes, total_bytes)``.
    """

    def __init__(self, threshold: Optional[float] = None,
                 refresh_ms: Optional[float] = None,
                 usage_reader: Optional[Callable[[], tuple]] = None):
        self.threshold = (threshold if threshold is not None
                          else float(_config.get("memory_usage_threshold")))
        self.refresh_ms = (refresh_ms if refresh_ms is not None
                           else float(_config.get(
                               "memory_monitor_refresh_ms")))
        self._usage_reader = usage_reader or self._system_usage
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._used = 0  # raylint: guarded-by(self._lock)
        self._total = 0  # raylint: guarded-by(self._lock)
        self._over = False  # raylint: guarded-by(self._lock)
        self._sampled_at = 0.0
        if self.enabled:
            self._sample()  # first decision must not wait a full period

    @property
    def enabled(self) -> bool:
        return self.refresh_ms > 0

    @staticmethod
    def _system_usage() -> tuple:
        """(used_bytes, total_bytes) from the tighter of host meminfo
        and the cgroup limit."""
        info = _read_meminfo_kb()
        total = info.get("MemTotal", 0) * 1024
        avail = info.get("MemAvailable", 0) * 1024
        used = max(0, total - avail)
        climit = _read_cgroup_limit_bytes()
        if climit and (total == 0 or climit < total):
            ccur = _read_cgroup_current_bytes()
            if ccur is not None:
                return ccur, climit
        return used, total

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.refresh_ms / 1000.0):
            try:
                self._sample()
            except Exception as e:  # noqa: BLE001 - monitor must never die
                logger.debug("memory sample failed: %s", e)

    def _sample(self):
        used, total = self._usage_reader()
        with self._lock:
            self._used, self._total = used, total  # raylint: guarded-by(self._lock)
            self._over = bool(total) and (used / total) >= self.threshold
            self._sampled_at = time.monotonic()  # raylint: guarded-by(self._lock)

    # -- queries ---------------------------------------------------------
    def is_over_threshold(self) -> bool:
        if not self.enabled:
            return False
        with self._lock:
            return self._over

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "threshold": self.threshold,
                "used_mb": round(self._used / (1 << 20), 1),
                "total_mb": round(self._total / (1 << 20), 1),
                "used_frac": (round(self._used / self._total, 4)
                              if self._total else 0.0),
                "rss_mb": round(_read_self_rss_kb() / 1024.0, 1),
                "over_threshold": self._over,
            }


_config.define("memory_usage_threshold", float, 0.95,
               "fraction of usable host/cgroup memory above which a "
               "daemon sheds new task admissions (OOM guard)")
_config.define("memory_monitor_refresh_ms", int, 250,
               "memory monitor sampling period; <= 0 disables it")
