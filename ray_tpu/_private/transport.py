"""Shared striped transport: the single owner of bulk-bytes data sockets.

Every path that moves bulk payload bytes between peers — object fetch
(``FETCH_OBJECT``), proactive/drain object push (``PUSH_OBJECT``), and
checkpoint chunk fetch on restore — stripes over ONE per-peer pool of raw
data connections (:class:`_DataStreamPool`, defined here and only here).
The reference separates object-manager data connections from the raylet
control channel for the same reason: a multi-GB transfer must not
head-of-line-block the multiplexed control socket, and one socket's
reader thread must not serialize a transfer that could ride N streams.

Auto-tuning: instead of fixed defaults, a one-shot loopback bandwidth
probe (:func:`ensure_probed`) measures send throughput at several chunk
sizes and derives

- ``fetch_chunk_bytes``   — the chunk size with the best measured rate,
- ``SO_SNDBUF/SO_RCVBUF`` — two in-flight chunks per stream, and
- streams per peer        — enough to overlap send/recv work without
  oversubscribing the host's cores.

Explicit config knobs always win; the probe only fills the ``0``/"auto"
holes. The probe result is exported to the bench as
``transport_probe_gbps`` (see :func:`probe_report`).

Failover: :class:`StripedTransfer` owns the retry loop shared by all
consumers — chunks queued on a stream that dies mid-transfer are retried
on the surviving/replenished streams under the standard backoff policy,
and the ``transport.stream`` chaos point fires per chunk submission so a
deterministic schedule can kill any stripe of any consumer.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

from ray_tpu import chaos
from ray_tpu.observability import comms, perf
from ray_tpu._private.backoff import BackoffPolicy
from ray_tpu._private.config import _config
from ray_tpu._private.rpc import (RpcClient, RpcConnectionError,
                                  RpcRemoteError)

# raylint: hot-path  (bulk-transfer module: R8 flags hidden payload copies)

logger = logging.getLogger("ray_tpu")

#: Fallback chunk size when the knob is 0/auto and the probe is disabled.
DEFAULT_CHUNK = 8 * 1024 * 1024

#: Chunk sizes the probe races against each other.
_PROBE_CANDIDATES = (1 << 20, 4 << 20, 8 << 20, 16 << 20)

_tuned_lock = threading.Lock()
_tuned: Dict[str, float] = {}   # chunk_bytes, sock_buf, streams, probe_gbps


# -- auto-tune probe ----------------------------------------------------------

def _probe_one(nbytes: int, chunk: int) -> float:
    """Throughput (bytes/s) of a loopback send of ``nbytes`` in ``chunk``
    pieces — the syscall/copy cost profile of one data stream."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        drained = threading.Event()

        def _drain():
            conn, _ = srv.accept()
            buf = bytearray(min(chunk, 1 << 20))
            view = memoryview(buf)
            with conn:
                while conn.recv_into(view):
                    pass
            drained.set()

        th = threading.Thread(target=_drain, name="transport-probe",
                              daemon=True)
        th.start()
        cli = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            cli.connect(srv.getsockname())
            cli.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            payload = memoryview(bytearray(chunk))
            sent = 0
            t0 = time.perf_counter()
            while sent < nbytes:
                n = min(chunk, nbytes - sent)
                cli.sendall(payload[:n])
                sent += n
        finally:
            cli.close()
        drained.wait(timeout=10.0)
        el = time.perf_counter() - t0
        return nbytes / el if el > 0 else 0.0
    finally:
        srv.close()


def ensure_probed() -> None:
    """Run the startup bandwidth probe once per process (thread-safe).

    Disabled (``transport_probe_bytes=0``) or failed probes leave the
    static fallbacks in place — auto-tuning is an optimization, never a
    prerequisite for moving bytes."""
    with _tuned_lock:
        if _tuned:
            return
        # marks the attempt below: the probe runs once
        _tuned["probe_gbps"] = 0.0  # raylint: allow(data-race) unlocked readers see a GIL-atomic dict snapshot; a miss falls back to static defaults
        nbytes = int(_config.get("transport_probe_bytes"))
        if nbytes <= 0:
            return
        try:
            best_chunk, best_rate = 0, 0.0
            for chunk in _PROBE_CANDIDATES:
                if chunk > nbytes:
                    continue  # larger than the whole probe: measures nothing
                rate = _probe_one(nbytes, chunk)
                if rate > best_rate:
                    best_chunk, best_rate = chunk, rate
            if not best_chunk:
                return
            ncpu = os.cpu_count() or 4
            # raylint: allow(data-race) unlocked readers see a GIL-atomic dict snapshot; a miss falls back to static defaults
            _tuned.update(
                chunk_bytes=best_chunk,
                sock_buf=min(max(2 * best_chunk, 1 << 20), 64 << 20),
                streams=4 if ncpu >= 4 else 2,
                probe_gbps=best_rate / 1e9)
            logger.debug(
                "transport probe: %.2f GB/s, chunk=%d MiB, streams=%d",
                best_rate / 1e9, best_chunk >> 20, int(_tuned["streams"]))
        except OSError as e:
            logger.warning("transport bandwidth probe failed (%s); "
                           "using static defaults", e)


def probe_report() -> Dict[str, float]:
    """Tuned values for the bench/doctor (runs the probe if needed)."""
    ensure_probed()
    with _tuned_lock:
        return dict(_tuned)


def _reset_probe_for_tests() -> None:
    with _tuned_lock:
        _tuned.clear()  # raylint: allow(data-race) test-only reset; unlocked readers fall back to static defaults


# -- knob resolution (explicit value wins; probe fills the "auto" holes) ------

def fetch_chunk_bytes() -> int:
    """Bulk-transfer chunk size: the single source of truth for every
    consumer (object fetch/push, checkpoint fetch, drain migration)."""
    n = int(_config.get("fetch_chunk_bytes"))
    if n > 0:
        return n
    ensure_probed()
    return int(_tuned.get("chunk_bytes") or DEFAULT_CHUNK)


def data_sock_buf() -> int:
    """SO_SNDBUF/SO_RCVBUF for bulk-transfer sockets: explicit knob, else
    the probe's pick, else sized to one fetch chunk so a whole chunk can
    be in flight per stream (the kernel silently caps at
    net.core.[rw]mem_max)."""
    n = int(_config.get("data_socket_buffer_bytes"))
    if n > 0:
        return n
    ensure_probed()
    tuned = int(_tuned.get("sock_buf") or 0)
    if tuned:
        return tuned
    return min(max(fetch_chunk_bytes(), 1 << 20), 64 << 20)


def streams_per_peer() -> int:
    """Data streams per peer: >0 explicit, 0 pool disabled, <0 auto."""
    n = int(_config.get("data_streams_per_peer"))
    if n >= 0:
        return n
    ensure_probed()
    return int(_tuned.get("streams") or 4)


# -- the pool -----------------------------------------------------------------

class _DataStreamPool:
    """Per-peer pool of raw data connections (``data_streams_per_peer``).

    Chunked bulk transfers stripe across these instead of serializing
    behind the multiplexed control socket's single reader/writer — the
    reference separates object-manager data connections from the raylet
    control channel for the same reason. Streams are plain authenticated
    ``RpcClient``s (same FETCH_OBJECT/PUSH_OBJECT protocol), created
    lazily per peer and replaced on failure; with the pool disabled
    (size 0) callers fall back to the control connection."""

    def __init__(self):
        self._lock = threading.Lock()
        self._streams: Dict[str, List[RpcClient]] = {}  # raylint: guarded-by(self._lock)

    def clients(self, address: str) -> List[RpcClient]:
        n = streams_per_peer()
        if n <= 0:
            return []
        extra: List[RpcClient] = []
        with self._lock:
            pool = [c for c in self._streams.get(address, ())
                    if not c.closed]
            # the knob is live (the autopilot retunes it from the link
            # matrix): a shrink closes the surplus lanes instead of
            # pinning the old width for the peer's lifetime
            if len(pool) > n:
                extra, pool = pool[n:], pool[:n]
            while len(pool) < n:
                try:
                    pool.append(RpcClient(
                        address, sock_buf_bytes=data_sock_buf()))
                except (OSError, RpcConnectionError):
                    break  # peer unreachable: callers use what exists
            self._streams[address] = pool
        for c in extra:  # close outside the lock: close() can block
            c.close()
        return list(pool)

    def drop(self, address: str) -> None:
        with self._lock:
            pool = self._streams.pop(address, [])
        for c in pool:
            c.close()

    def close_all(self) -> None:
        with self._lock:
            pools = list(self._streams.values())
            self._streams.clear()
        for pool in pools:
            for c in pool:
                c.close()


# -- shared striped submission with failover ----------------------------------

class StripedTransfer:
    """One striped bulk transfer to/from ``addr`` over a shared pool.

    The caller supplies ``submit(client, offset, done_cb)`` which issues
    one async chunk request on ``client`` and arranges for
    ``done_cb(error_or_none)`` to run when that chunk settles; this class
    owns everything else: round-robin striping, the ``transport.stream``
    chaos point, completion accounting, and the failover loop — failed
    chunks are retried on the surviving/replenished streams under the
    standard backoff policy. Errors of a type in ``fatal`` abort the
    transfer immediately (the peer authoritatively lost the data; no
    retry can help). ``self.streams`` always holds the streams of the
    most recent round so callers can quiesce their readers on abort.
    """

    def __init__(self, pool: _DataStreamPool, addr: str, *,
                 consumer: str, fallback_client: Optional[RpcClient] = None,
                 streams: Optional[List[RpcClient]] = None,
                 timeout: float = 120.0):
        self.pool = pool
        self.addr = addr
        self.consumer = consumer
        self.fallback = fallback_client
        self.timeout = timeout
        self.streams: List[RpcClient] = list(streams) if streams else []

    def _refill(self) -> None:
        self.streams = [c for c in self.pool.clients(self.addr)
                        if not c.closed]
        if not self.streams:
            if self.fallback is None:
                raise RpcConnectionError(
                    f"data streams to {self.addr} lost mid-transfer")
            self.streams = [self.fallback]

    def run(self, offsets: Iterable[int],
            submit: Callable[[RpcClient, int, Callable], None],
            fatal: tuple = (RpcRemoteError,)) -> None:
        if not perf.ENABLED:
            return self._run(offsets, submit, fatal)
        t0 = time.monotonic()
        try:
            return self._run(offsets, submit, fatal)
        finally:
            perf.observe("transport.striped_run",
                         (time.monotonic() - t0) * 1e3)

    def _run(self, offsets: Iterable[int],
             submit: Callable[[RpcClient, int, Callable], None],
             fatal: tuple = (RpcRemoteError,)) -> None:
        pending = list(offsets)
        if not pending:
            return
        if not self.streams:
            self._refill()
        backoff = BackoffPolicy(
            deadline_s=_config.get("backoff_deadline_s")).start()
        while True:
            state = {"errors": {}, "left": len(pending)}
            state_lock = threading.Lock()  # NOT any runtime lock: cbs run
            done = threading.Event()       # on stream reader threads

            def _settle(off, error):
                with state_lock:
                    if error is not None:
                        state["errors"][off] = error
                    state["left"] -= 1
                    if state["left"] == 0:
                        done.set()

            def _done_cb(off):
                if not (perf.ENABLED or comms.ENABLED):
                    return lambda error: _settle(off, error)
                t0 = time.monotonic()  # created immediately before submit

                def _cb(error, _t0=t0, _off=off):
                    dur = time.monotonic() - _t0
                    if perf.ENABLED:
                        perf.observe("transport.chunk", dur * 1e3)
                    if comms.ENABLED and error is None:
                        # Link matrix: successful chunks only (failed
                        # ones show up as retries below).  Chunk size is
                        # the configured stripe size — an estimate for
                        # the final partial chunk of a transfer.
                        comms.link_observe(self.addr, self.consumer,
                                           nbytes=fetch_chunk_bytes(),
                                           seconds=dur, chunks=1)
                    _settle(_off, error)
                return _cb

            for i, off in enumerate(pending):
                if chaos.ENABLED:
                    try:
                        act = chaos.inject(
                            "transport.stream", peer=self.addr,
                            consumer=self.consumer, offset=str(off))
                    except chaos.ChaosConnectionReset as e:
                        _settle(off, RpcConnectionError(str(e)))
                        continue
                    if act == "drop":
                        _settle(off, RpcConnectionError(
                            "chaos: stripe dropped"))
                        continue
                try:
                    submit(self.streams[i % len(self.streams)], off,
                           _done_cb(off))
                except Exception as e:  # noqa: BLE001 — dead stream at send
                    _settle(off, e)
            if not done.wait(timeout=self.timeout):
                raise TimeoutError(
                    f"striped {self.consumer} transfer with {self.addr} "
                    f"timed out after {self.timeout}s")
            errors = state["errors"]
            if not errors:
                return
            for err in errors.values():
                if isinstance(err, fatal):
                    raise err
            # Transport failures: retry just the failed chunks on the
            # surviving streams (clients() replaces dead ones).
            pending = sorted(errors)
            if comms.ENABLED:
                # One failover per retry round (streams get replaced),
                # plus the chunks it re-sends — the link-health signal
                # the doctor's link-matrix outlier rule keys on.
                comms.link_observe(self.addr, self.consumer,
                                   retries=len(pending), failovers=1)
            if not backoff.sleep():
                err = next(iter(errors.values()))
                if isinstance(err, (RpcConnectionError, TimeoutError)):
                    raise err
                raise RpcConnectionError(str(err))
            self._refill()
