"""JSON state endpoint served from the driver process.

The headless analogue of the reference's dashboard head + state
aggregator (``dashboard/head.py:63``, ``dashboard/state_aggregator.py``):
one HTTP server in the device-owner process exposing cluster state as
JSON plus Prometheus ``/metrics``. The CLI (``ray_tpu.scripts.cli``)
discovers the port through a session file, like the reference's session
directory.

Endpoints:
  /api/status    — node/actor/task counts + resources
  /api/tasks     /api/actors    /api/nodes    /api/objects    /api/pgs
  /api/events    — structured event ring
  /api/timeline  — chrome-tracing JSON of task/actor spans
  /metrics       — Prometheus text exposition
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

SESSION_DIR = "/tmp/ray_tpu"
PORT_FILE = os.path.join(SESSION_DIR, "state_server_port")

_server_lock = threading.Lock()
_server = None  # raylint: guarded-by(_server_lock)


def start_state_server(port: int = 0) -> int:
    """Start the server on a daemon thread; returns the bound port and
    writes it to the session port file."""
    global _server
    import http.server

    from ray_tpu.experimental.state import api as state_api
    from ray_tpu.util import metrics as metrics_mod

    class Handler(http.server.BaseHTTPRequestHandler):
        def _json(self, payload, code=200):
            body = json.dumps(payload, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            try:
                if self.path == "/metrics":
                    body = metrics_mod.generate_prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/api/status":
                    self._json(cluster_status())
                elif self.path == "/api/tasks":
                    self._json(state_api.list_tasks())
                elif self.path == "/api/actors":
                    self._json(state_api.list_actors())
                elif self.path == "/api/nodes":
                    self._json(state_api.list_nodes())
                elif self.path == "/api/objects":
                    self._json(state_api.list_objects())
                elif self.path == "/api/pgs":
                    self._json(state_api.list_placement_groups())
                elif self.path == "/api/events":
                    self._json(state_api.list_events())
                elif self.path == "/api/timeline":
                    from ray_tpu._private.profiling import dump_timeline
                    self._json(dump_timeline())
                else:
                    self._json({"error": "unknown endpoint"}, code=404)
            except Exception as e:  # pragma: no cover - defensive
                self._json({"error": repr(e)}, code=500)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    with _server_lock:
        _server = srv
    bound = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="state-server").start()
    os.makedirs(SESSION_DIR, exist_ok=True)
    with open(PORT_FILE, "w") as f:
        f.write(str(bound))
    return bound


def stop_state_server():
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()  # release the listening socket now, not at GC
        try:
            os.unlink(PORT_FILE)
        except OSError:
            pass


def discover_port() -> Optional[int]:
    try:
        with open(PORT_FILE) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def cluster_status() -> dict:
    """The ``ray status`` payload: nodes, resource totals, task/actor
    summaries (reference: ``scripts.py:1461`` status command)."""
    from ray_tpu._private import worker as _worker
    from ray_tpu.experimental.state import api as state_api
    rt = _worker.try_global_runtime()
    if rt is None:
        return {"initialized": False}
    return {
        "initialized": True,
        "nodes": state_api.list_nodes(),
        "task_summary": state_api.summarize_tasks(),
        "actor_summary": state_api.summarize_actors(),
        "cluster_resources": _worker.cluster_resources(),
        "available_resources": _worker.available_resources(),
    }
