"""Cluster scheduling policies.

Parity with the reference's pluggable policy library
(``src/ray/raylet/scheduling/policy/``):

- ``HybridPolicy`` — the default: pack onto the first (local-preferred) nodes
  until a utilization threshold, then spread; randomized top-k pick
  (``hybrid_scheduling_policy.h:48``).
- ``SpreadPolicy`` — round-robin over feasible nodes
  (``spread_scheduling_policy.h:27``).
- ``NodeAffinityPolicy`` — hard/soft pinning to one node
  (``node_affinity_scheduling_policy.h:29``).
- Bundle policies for placement groups: PACK / SPREAD / STRICT_PACK /
  STRICT_SPREAD (``bundle_scheduling_policy.h:73-97``).

All policies are pure functions over a snapshot of node states so they are
shared by the cluster scheduler and the placement-group manager, like the
reference shares them between raylet and GCS.
"""

from __future__ import annotations
import logging

import random
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.config import _config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.resources import NodeResources, ResourceSet

logger = logging.getLogger("ray_tpu")

_native_sched = None
_native_checked = False


def _native():
    """The C++ policy kernels (``_native/scheduling.cc``), or None."""
    global _native_sched, _native_checked
    if not _native_checked:
        _native_checked = True  # raylint: allow(data-race) idempotent lazy probe; a racing double-load yields equivalent handles
        if _config.get("use_native_scheduler"):
            try:
                from ray_tpu._native.build import load_native_library
                _native_sched = load_native_library("scheduling")  # raylint: allow(data-race) idempotent lazy probe; a racing double-load yields equivalent handles
                if _native_sched is not None:
                    import ctypes
                    dp = ctypes.POINTER(ctypes.c_double)
                    up = ctypes.POINTER(ctypes.c_uint8)
                    i64 = ctypes.c_int64
                    _native_sched.sched_hybrid_select.restype = i64
                    _native_sched.sched_hybrid_select.argtypes = [
                        dp, dp, up, dp, i64, i64, i64,
                        ctypes.c_double, ctypes.c_double, i64]
                    _native_sched.sched_spread_select.restype = i64
                    _native_sched.sched_spread_select.argtypes = [
                        dp, up, dp, i64, i64, i64]
            except Exception as e:
                logger.warning("native scheduling lib unavailable: %s", e)
                _native_sched = None  # raylint: allow(data-race) idempotent lazy probe; a racing double-load yields equivalent handles
    return _native_sched


def _flatten(nodes: Sequence["NodeState"], request: ResourceSet):
    """Dense (available, total, alive, request) arrays over the union of
    resource keys, for the native kernels."""
    import ctypes
    keys = list(request.to_dict().keys())
    seen = set(keys)
    for n in nodes:
        for k in n.resources.total.to_dict():
            if k not in seen:
                seen.add(k)
                keys.append(k)
    n_nodes, n_res = len(nodes), max(1, len(keys))
    avail = (ctypes.c_double * (n_nodes * n_res))()
    total = (ctypes.c_double * (n_nodes * n_res))()
    alive = (ctypes.c_uint8 * n_nodes)()
    req = (ctypes.c_double * n_res)()
    req_d = request.to_dict()
    for j, k in enumerate(keys):
        req[j] = req_d.get(k, 0.0)
    for i, n in enumerate(nodes):
        # Schedulability, not liveness: the native kernels need no notion
        # of DRAINING — a draining node simply reads as ineligible.
        alive[i] = 1 if n.schedulable else 0
        a = n.resources.available.to_dict()
        t = n.resources.total.to_dict()
        for j, k in enumerate(keys):
            avail[i * n_res + j] = a.get(k, 0.0)
            total[i * n_res + j] = t.get(k, 0.0)
    return avail, total, alive, req, n_nodes, n_res


class NodeState:
    """Scheduler-visible view of one node."""

    def __init__(self, node_id: NodeID, resources: NodeResources, alive: bool = True,
                 draining: bool = False, pending_drain: bool = False):
        self.node_id = node_id
        self.resources = resources
        self.alive = alive
        self.draining = draining
        # Hazard hint from the autoscaler's preemption estimator: the
        # node is still fully schedulable, but a drain is likely soon, so
        # policies place on it only when no stable node fits.
        self.pending_drain = pending_drain

    @property
    def schedulable(self) -> bool:
        """Eligible for NEW placement. A DRAINING node is still alive (its
        in-flight work runs to the drain deadline) but must not receive
        anything new, so every policy filters on this, not ``alive``."""
        return self.alive and not self.draining


def _stable_first(nodes: Sequence["NodeState"]) -> Optional[List["NodeState"]]:
    """The subset of ``nodes`` without a pending-drain hazard hint, or
    None when the hint splits nothing (all stable / all hazardous).

    Every placement policy tries the stable subset first and falls back
    to the full set: new work should land on capacity that is expected
    to survive, but a fully-hazardous fleet must still schedule."""
    stable = [n for n in nodes if not n.pending_drain]
    if not stable or len(stable) == len(nodes):
        return None
    return stable


class Infeasible(Exception):
    """No node in the cluster could ever satisfy the request."""


class HybridPolicy:
    """Pack-then-spread with top-k randomization."""

    def __init__(self, spread_threshold: Optional[float] = None,
                 top_k_fraction: Optional[float] = None, seed: Optional[int] = None):
        self.spread_threshold = spread_threshold
        self.top_k_fraction = top_k_fraction
        self._rng = random.Random(seed)

    def select(self, nodes: Sequence[NodeState], request: ResourceSet,
               preferred: Optional[NodeID] = None) -> Optional[NodeID]:
        stable = _stable_first(nodes)
        if stable is not None:
            nid = self._select(stable, request, preferred)
            if nid is not None:
                return nid
        return self._select(nodes, request, preferred)

    def _select(self, nodes: Sequence[NodeState], request: ResourceSet,
                preferred: Optional[NodeID] = None) -> Optional[NodeID]:
        threshold = (self.spread_threshold if self.spread_threshold is not None
                     else _config.get("scheduler_spread_threshold"))
        top_k_frac = (self.top_k_fraction if self.top_k_fraction is not None
                      else _config.get("scheduler_top_k_fraction"))
        lib = _native()
        if lib is not None:
            avail, total, alive, req, n_nodes, n_res = _flatten(nodes,
                                                                request)
            preferred_idx = -1
            if preferred is not None:
                for i, n in enumerate(nodes):
                    if n.node_id == preferred:
                        preferred_idx = i
                        break
            idx = lib.sched_hybrid_select(
                avail, total, alive, req, n_nodes, n_res, preferred_idx,
                threshold, top_k_frac, self._rng.getrandbits(62))
            return nodes[idx].node_id if idx >= 0 else None
        scored: List[Tuple[float, int, NodeID]] = []
        for i, n in enumerate(nodes):
            if not n.schedulable or not n.resources.can_fit(request):
                continue
            util = n.resources.utilization()
            # Below threshold: score 0 (pack anywhere cheap); above: score by
            # utilization so lighter nodes win (spread).
            score = 0.0 if util < threshold else util
            is_preferred = 0 if (preferred is not None and n.node_id == preferred) else 1
            scored.append((score, is_preferred, i, n.node_id))
        if not scored:
            return None
        scored.sort(key=lambda t: (t[0], t[1], t[2]))
        k = max(1, int(len(scored) * top_k_frac))
        return self._rng.choice(scored[:k])[3]


class SpreadPolicy:
    def __init__(self):
        self._next = 0  # raylint: guarded-by(self._lock)
        self._lock = threading.Lock()

    def select(self, nodes: Sequence[NodeState], request: ResourceSet,
               preferred: Optional[NodeID] = None) -> Optional[NodeID]:
        stable = _stable_first(nodes)
        if stable is not None:
            nid = self._select(stable, request, preferred)
            if nid is not None:
                return nid
        return self._select(nodes, request, preferred)

    def _select(self, nodes: Sequence[NodeState], request: ResourceSet,
                preferred: Optional[NodeID] = None) -> Optional[NodeID]:
        lib = _native()
        if lib is not None:
            avail, _total, alive, req, n_nodes, n_res = _flatten(nodes,
                                                                 request)
            with self._lock:
                cursor = self._next
                self._next += 1  # raylint: guarded-by(self._lock)
            idx = lib.sched_spread_select(avail, alive, req, n_nodes,
                                          n_res, cursor)
            return nodes[idx].node_id if idx >= 0 else None
        feasible = [n for n in nodes
                    if n.schedulable and n.resources.can_fit(request)]
        if not feasible:
            return None
        with self._lock:
            choice = feasible[self._next % len(feasible)]
            self._next += 1
        return choice.node_id


class NodeAffinityPolicy:
    def select(self, nodes: Sequence[NodeState], request: ResourceSet,
               node_id_hex: str = "", soft: bool = False) -> Optional[NodeID]:
        target = None
        for n in nodes:
            if n.node_id.hex() == node_id_hex:
                target = n
                break
        if target is not None and target.schedulable:
            if target.resources.can_fit(request):
                return target.node_id
            if target.resources.could_ever_fit(request):
                return None  # node is busy: wait, don't fail (reference
                # semantics fail hard affinity only when the node is gone)
        if not soft:
            raise Infeasible(f"node {node_id_hex} unavailable for hard affinity")
        return HybridPolicy().select(nodes, request)


# -- bundle (placement group) policies ---------------------------------------


def _bin_pack(nodes: List[NodeState], bundles: Sequence[ResourceSet],
              distinct: bool, minimize_nodes: bool) -> Optional[List[NodeID]]:
    """Greedy bundle placement over a copy of node availability."""
    avail: Dict[NodeID, ResourceSet] = {
        n.node_id: n.resources.available for n in nodes if n.schedulable}
    pending: Dict[NodeID, bool] = {
        n.node_id: n.pending_drain for n in nodes if n.schedulable}
    used_nodes: List[NodeID] = []
    placement: List[NodeID] = []
    order = sorted(range(len(bundles)),
                   key=lambda i: -sum(bundles[i].to_dict().values()))
    slots: List[Optional[NodeID]] = [None] * len(bundles)
    for i in order:
        b = bundles[i]
        candidates = []
        for nid, a in avail.items():
            if distinct and nid in used_nodes:
                continue
            if b.is_subset_of(a):
                candidates.append(nid)
        if not candidates:
            return None
        if minimize_nodes:
            # Pending-drain nodes last, then prefer nodes already holding
            # a bundle (PACK).
            candidates.sort(key=lambda nid: (pending[nid],
                                             nid not in used_nodes))
        else:
            # SPREAD: pending-drain nodes last, then prefer nodes not yet
            # holding a bundle.
            candidates.sort(key=lambda nid: (pending[nid],
                                             nid in used_nodes))
        chosen = candidates[0]
        avail[chosen] = avail[chosen].subtract(b)
        if chosen not in used_nodes:
            used_nodes.append(chosen)
        slots[i] = chosen
    return slots  # type: ignore[return-value]


def schedule_bundles(nodes: List[NodeState], bundles: Sequence[ResourceSet],
                     strategy: str) -> Optional[List[NodeID]]:
    """Return one NodeID per bundle, or None if unplaceable now."""
    if strategy == "STRICT_PACK":
        total = ResourceSet()
        for b in bundles:
            total = total.add(b)
        # Stable nodes first: a strict-pack group on a pending-drain node
        # would migrate wholesale at the predicted preemption.
        for n in sorted(nodes, key=lambda n: n.pending_drain):
            if n.schedulable and n.resources.can_fit(total):
                return [n.node_id] * len(bundles)
        return None
    if strategy == "STRICT_SPREAD":
        return _bin_pack(nodes, bundles, distinct=True, minimize_nodes=False)
    if strategy == "PACK":
        return _bin_pack(nodes, bundles, distinct=False, minimize_nodes=True)
    if strategy == "SPREAD":
        return _bin_pack(nodes, bundles, distinct=False, minimize_nodes=False)
    raise ValueError(f"unknown placement strategy {strategy}")
