"""Task/actor span recording and chrome-tracing export.

Parity with the reference's timeline pipeline: per-worker profile events
(``src/ray/core_worker/profiling.h:30``) aggregated by
``GlobalState.chrome_tracing_dump`` (``python/ray/_private/state.py:419``)
behind the ``ray timeline`` CLI (``scripts.py:1755``). Spans are recorded
in-process (the host-granular runtime has no cross-process hop) and
dumped in the chrome://tracing "X" (complete-event) format.

For device-side detail the TPU story is strictly better than py-spy:
``start_device_trace``/``stop_device_trace`` wrap ``jax.profiler`` so an
XLA trace (HLO timings, HBM usage) lands next to the host spans.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from ray_tpu._private.config import _config


class Profiler:
    """Bounded in-memory span ring. Thread-safe, cheap when disabled.

    Eviction is drop-oldest (a true ring): when the buffer is full the
    oldest span falls off and ``dropped`` is bumped, so the tail of the
    timeline — the part an operator is usually debugging — is never lost
    to a bulk eviction. ``chrome_trace``/``dump`` copy under the lock, so
    they are safe while other threads keep recording.
    """

    def __init__(self, max_spans: Optional[int] = None):
        self._lock = threading.Lock()
        if max_spans is None:
            max_spans = int(_config.get("trace_ring_size"))
        self._max = max_spans
        self._spans: Deque[dict] = collections.deque(maxlen=max_spans)
        self._dropped = 0
        # Monotonic append counter — survives clear() so incremental
        # readers (the flight recorder's spool thread) never double-read.
        self._seq = 0

    @property
    def enabled(self) -> bool:
        return bool(_config.get("profiling_enabled"))

    @property
    def dropped(self) -> int:
        """Spans evicted by the ring since the last clear()."""
        return self._dropped

    def record(self, name: str, cat: str, pid: str, start_s: float,
               dur_s: float, args: Optional[Dict[str, Any]] = None):
        if not self.enabled:
            return
        span = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "pid": pid,
            "tid": threading.current_thread().name,
            "ts": start_s * 1e6,
            "dur": dur_s * 1e6,
        }
        if args:
            span["args"] = args
        self._append(span)

    def instant(self, name: str, cat: str, pid: str,
                args: Optional[Dict[str, Any]] = None,
                ts_s: Optional[float] = None):
        """Record a chrome instant event ("i" phase) — a point in time
        (chaos injection, breaker flip) rather than a duration."""
        if not self.enabled:
            return
        event = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "p",  # process-scoped instant marker
            "pid": pid,
            "tid": threading.current_thread().name,
            "ts": (time.time() if ts_s is None else ts_s) * 1e6,
        }
        if args:
            event["args"] = args
        self._append(event)

    def _append(self, span: dict):
        with self._lock:
            dropped = len(self._spans) == self._max
            if dropped:
                self._dropped += 1
            self._spans.append(span)
            self._seq += 1
        if dropped:  # metric bump outside the ring lock (own lock inside)
            _spans_dropped_metric()

    def chrome_trace(self) -> List[dict]:
        with self._lock:
            return list(self._spans)

    def events_since(self, cursor: int) -> "tuple[int, List[dict]]":
        """Incremental read: events appended after ``cursor`` (a value
        previously returned by this method; start from 0). Returns
        ``(new_cursor, events)``. Events that fell off the ring between
        reads are lost — the spool cadence bounds that window."""
        with self._lock:
            new = self._seq - cursor
            if new <= 0:
                return self._seq, []
            if new > len(self._spans):
                new = len(self._spans)
            tail = list(self._spans)[-new:] if new else []
            return self._seq, tail

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._dropped = 0


_dropped_counter = None


def _spans_dropped_metric():
    # Lazy: metrics imports config; keep profiling importable standalone.
    global _dropped_counter
    if _dropped_counter is None:
        from ray_tpu.util.metrics import Counter
        _dropped_counter = Counter(
            "profiler_spans_dropped",
            "Spans evicted from the bounded span ring")
    _dropped_counter.inc()


_profiler = Profiler()


def get_profiler() -> Profiler:
    return _profiler


def dump_timeline(filename: Optional[str] = None) -> Any:
    """Chrome-tracing dump of recorded spans (``ray timeline``,
    ``state.py:419``). Returns the event list, or writes it to
    ``filename`` and returns the path. Safe while recording continues:
    the span list is snapshotted under the ring lock before writing."""
    trace = _profiler.chrome_trace()
    if filename is None:
        return trace
    # Atomic: a crash mid-dump must not leave a torn half-JSON file where
    # an operator expects a readable timeline (tmp + fsync + rename).
    from ray_tpu.checkpoint.manifest import atomic_write_bytes
    atomic_write_bytes(filename, json.dumps(trace).encode())
    return filename


# -- device-side tracing ----------------------------------------------------

_device_trace_dir: Optional[str] = None


def start_device_trace(log_dir: str) -> None:
    """Begin an XLA profiler trace (TPU timeline; jax.profiler)."""
    global _device_trace_dir
    import jax
    jax.profiler.start_trace(log_dir)
    _device_trace_dir = log_dir


def stop_device_trace() -> Optional[str]:
    global _device_trace_dir
    import jax
    jax.profiler.stop_trace()
    out, _device_trace_dir = _device_trace_dir, None
    return out


class profile_span:
    """Context manager for user code spans (reference:
    ``ray.profiling.profile`` events, ``_raylet.pyx:1613``).

    Records under the REAL process identity (``observability.process_label``
    — daemons relabel to ``node:<hex8>``), and when tracing is on the span
    routes through :class:`observability.span` so user phases parent into
    the active distributed trace instead of floating beside it."""

    def __init__(self, name: str, cat: str = "user",
                 args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.args = args
        self._span = None

    def __enter__(self):
        # Lazy import: observability imports this module at load time.
        from ray_tpu import observability
        if observability.ENABLED:
            # raylint: allow(span-leak) delegated CM: our __exit__ closes it
            self._span = observability.span(
                self.name, cat=self.cat, **(self.args or {}))
            self._span.__enter__()
        else:
            self._t0 = time.time()
        return self

    def __exit__(self, *exc_info):
        if self._span is not None:
            span, self._span = self._span, None
            return span.__exit__(*exc_info)
        from ray_tpu import observability
        _profiler.record(self.name, self.cat,
                         pid=observability.process_label(),
                         start_s=self._t0, dur_s=time.time() - self._t0,
                         args=self.args)
