"""Per-host process supervision: launch + babysit the cluster processes.

The production analogue of the reference's ``Node``
(``python/ray/_private/node.py:1061`` start_ray_processes /
process-failure policy): a head node runs the C++ state service plus one
host daemon; a worker node runs one host daemon. The supervisor restarts
a crashed child with exponential backoff — the state service recovers
its tables from journal+snapshot, daemons simply re-register as fresh
nodes (their node identity is per-incarnation by design: objects and
actors they hosted are recovered by their owners' lineage/restart
machinery, test_distributed_cluster.py).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional, Tuple

logger = logging.getLogger("ray_tpu")


def spawn_daemon(state_addr: str, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 heartbeat_s: float = 1.0,
                 tp_cpu_devices: int = 0,
                 labels: Optional[Dict[str, str]] = None,
                 startup_timeout_s: float = 60.0,
                 env_overrides: Optional[Dict[str, str]] = None
                 ) -> Tuple[subprocess.Popen, str]:
    """Start one host-daemon process; returns (process, rpc_address)."""
    ready = tempfile.mktemp(prefix="raytpu_daemon_ready_")
    cmd = [sys.executable, "-m", "ray_tpu._private.host_daemon",
           "--state-addr", state_addr,
           "--resources", json.dumps(resources or {}),
           "--labels", json.dumps(labels or {}),
           "--heartbeat-interval-s", str(heartbeat_s),
           "--ready-file", ready]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        cmd += ["--num-tpus", str(num_tpus)]
    env = dict(os.environ)
    env.update(env_overrides or {})
    if tp_cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env["RAY_TPU_TP_CPU_DEVICES"] = str(tp_cpu_devices)
        # jax_num_cpu_devices (set at tensor-plane join) loses to an
        # inherited force_host_platform_device_count; strip it so the
        # daemon gets exactly tp_cpu_devices devices.
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(flags)
    proc = subprocess.Popen(cmd, env=env)
    deadline = time.monotonic() + startup_timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(ready):
            with open(ready) as f:
                addr = f.read().strip()
            os.unlink(ready)
            return proc, addr
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon exited rc={proc.returncode} during startup")
        time.sleep(0.02)
    proc.kill()
    raise TimeoutError("daemon did not become ready")


class NodeSupervisor:
    """Runs in the foreground of a ``supervise`` process: owns the host's
    children and keeps them alive until told to stop."""

    RESTART_BACKOFF_S = (1.0, 2.0, 4.0, 8.0, 16.0, 30.0)
    STABLE_RESET_S = 60.0

    def __init__(self, run_dir: str, head: bool, state_addr: str = "",
                 num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 tp_cpu_devices: int = 0,
                 heartbeat_timeout_ms: float = 5000,
                 auth_token: str = ""):
        self.run_dir = run_dir
        self.head = head
        self.state_addr = state_addr
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.resources = resources or {}
        self.tp_cpu_devices = tp_cpu_devices
        self.heartbeat_timeout_ms = heartbeat_timeout_ms
        self.auth_token = auth_token
        self.state_proc: Optional[subprocess.Popen] = None
        self.daemon_proc: Optional[subprocess.Popen] = None
        self._stop = False
        os.makedirs(run_dir, exist_ok=True)

    # -- file plumbing -------------------------------------------------------

    def _write(self, name: str, value: str):
        path = os.path.join(self.run_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    # -- children ------------------------------------------------------------

    def _start_state_service(self):
        from ray_tpu._private.state_client import start_state_service
        data_dir = os.path.join(self.run_dir, "state")
        # A RESTART must come back on the same port — peers and drivers
        # hold the old address, and journal+snapshot recovery is pointless
        # if nobody can reach the recovered service.
        port = 0
        if self.state_addr:
            port = int(self.state_addr.rsplit(":", 1)[1])
        self.state_proc, self.state_addr = start_state_service(
            port=port, data_dir=data_dir,
            heartbeat_timeout_ms=self.heartbeat_timeout_ms)
        self._write("address", self.state_addr)
        self._write("state.pid", str(self.state_proc.pid))

    def _start_daemon(self):
        self.daemon_proc, addr = spawn_daemon(
            self.state_addr, num_cpus=self.num_cpus, num_tpus=self.num_tpus,
            resources=self.resources, tp_cpu_devices=self.tp_cpu_devices)
        self._write("daemon.pid", str(self.daemon_proc.pid))
        self._write("daemon.addr", addr)

    # -- main loop -----------------------------------------------------------

    def run(self):
        if self.auth_token:
            # Children (state service via getenv, daemons via inherited
            # env) and our own clients all read the shared secret from the
            # environment; see rpc.default_auth_token.
            os.environ["RAY_TPU_AUTH_TOKEN"] = self.auth_token
        self._write("supervisor.pid", str(os.getpid()))
        signal.signal(signal.SIGTERM, lambda *_: setattr(self, "_stop", True))
        signal.signal(signal.SIGINT, lambda *_: setattr(self, "_stop", True))
        if self.head:
            self._start_state_service()
        self._start_daemon()
        restarts = {"state": 0, "daemon": 0}
        last_restart = {"state": 0.0, "daemon": 0.0}
        logger.info("supervising %s node at %s (run dir %s)",
                    "head" if self.head else "worker", self.state_addr,
                    self.run_dir)
        while not self._stop:
            time.sleep(0.25)  # raylint: allow(bare-retry) liveness poll cadence; restarts pace via RESTART_BACKOFF_S
            now = time.monotonic()
            for name, proc, restart in (
                    ("state", self.state_proc,
                     self._start_state_service if self.head else None),
                    ("daemon", self.daemon_proc, self._start_daemon)):
                if restart is None or proc is None or proc.poll() is None:
                    continue
                if now - last_restart[name] > self.STABLE_RESET_S:
                    restarts[name] = 0
                backoff = self.RESTART_BACKOFF_S[
                    min(restarts[name], len(self.RESTART_BACKOFF_S) - 1)]
                logger.warning(
                    "%s exited rc=%s; restarting in %.1fs (attempt %d)",
                    name, proc.returncode, backoff, restarts[name] + 1)
                deadline = time.monotonic() + backoff
                while time.monotonic() < deadline and not self._stop:
                    time.sleep(0.1)  # raylint: allow(bare-retry) interruptible slice of the RESTART_BACKOFF_S wait
                if self._stop:
                    break
                try:
                    restart()
                    restarts[name] += 1
                    last_restart[name] = time.monotonic()
                except Exception:
                    logger.exception("restart of %s failed", name)
                    restarts[name] += 1
                    last_restart[name] = time.monotonic()
        self.shutdown()

    def shutdown(self):
        for proc in (self.daemon_proc, self.state_proc):
            if proc is not None and proc.poll() is None:
                proc.terminate()
        for proc in (self.daemon_proc, self.state_proc):
            if proc is not None:
                try:
                    proc.wait(timeout=10)
                except Exception as e:
                    logger.debug("graceful stop timed out; killing: %s", e)
                    proc.kill()
        for name in ("supervisor.pid", "daemon.pid", "state.pid",
                     "address", "daemon.addr"):
            try:
                os.unlink(os.path.join(self.run_dir, name))
            except OSError:
                pass
