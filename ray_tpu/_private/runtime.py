"""The host-granular distributed runtime.

This is the TPU-native redesign of the reference's L2 "kernel"
(GCS ``src/ray/gcs/gcs_server/gcs_server.h:70`` + raylet
``src/ray/raylet/node_manager.h`` + core_worker
``src/ray/core_worker/core_worker.h:63``), collapsed around one hard
hardware constraint: **a TPU host's devices are owned by exactly one
process** (libtpu is single-owner). So instead of process-per-worker with a
shared-memory arena between processes, the unit of distribution is the *host
runtime*: TPU tasks and actors execute as concurrency-scheduled threads
inside the device-owner process (the GIL is released for the duration of XLA
executions, so threads scale), device values stay resident as immutable
``jax.Array`` descriptors in the object store, and collectives are compiled
into the computation rather than invoked by the runtime.

What maps where:

- ``Runtime``   = GCS: node/actor/job/PG tables, internal KV, named actors,
                  object directory, heartbeat-style failure propagation.
- ``Node``      = raylet + plasma: resource accounting, admission (leases),
                  a worker pool (thread executor), a local object store.
- ``TaskManager`` = core_worker's TaskManager + ObjectRecoveryManager:
                  retries (``task_manager.h:152``) and lineage-based object
                  reconstruction (``object_recovery_manager.h:90``).
- ``ActorState``  = GcsActorManager entry + the actor's scheduling queue
                  (ordered mailbox; ``transport/actor_scheduling_queue.cc``),
                  with restart-up-to-``max_restarts``
                  (``gcs_actor_manager.h:66,433``).

Multi-host: each host runs one ``Runtime`` peer; the tensor plane between
hosts is JAX's multi-controller SPMD (``jax.distributed``), the control plane
is this module's state service reachable over gRPC (see
``ray_tpu/_private/state_service*``). In-process, ``cluster_utils.Cluster``
instantiates many ``Node``s under one ``Runtime`` for multi-node tests, like
the reference's ``python/ray/cluster_utils.py:99``.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu import chaos, observability
from ray_tpu import exceptions as exc
from ray_tpu.observability import perf
from ray_tpu.observability import recorder as _flight
from ray_tpu._private.backoff import BackoffPolicy
from ray_tpu._private.config import _config
from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID,
                                  PlacementGroupID, TaskID)
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.reference_counter import ReferenceCounter
from ray_tpu._private.resources import (CPU, TPU, NodeResources, ResourceSet)
from ray_tpu._private.scheduler import (HybridPolicy, Infeasible, NodeState,
                                        SpreadPolicy, schedule_bundles)
from ray_tpu._private.task_spec import TaskSpec

logger = logging.getLogger("ray_tpu")

_MAX_NODE_THREADS = 256


class _TaskContext(threading.local):
    def __init__(self):
        self.node_id: Optional[NodeID] = None
        self.task_id: Optional[TaskID] = None
        self.actor_id: Optional[ActorID] = None
        self.job_id: Optional[JobID] = None
        self.devices: Optional[list] = None
        self.placement_group: Any = None
        self.put_counter: int = 0
        self.cancel_flag: Optional[threading.Event] = None
        self.trace_id: str = ""   # current trace (propagates to children)
        self.span_id: str = ""    # current span (children's parent)


task_context = _TaskContext()

# Trace context for ASYNC actor methods: coroutines interleave on one
# loop thread, so a thread-local would be clobbered at every await —
# a ContextVar is copied per asyncio task instead. _attach_trace prefers
# it; sync paths (dedicated threads) keep using task_context.
import contextvars  # noqa: E402

_trace_var: "contextvars.ContextVar" = contextvars.ContextVar(
    "ray_tpu_trace", default=None)  # (trace_id, span_id) | None


def _obs_context_provider():
    """Expose the executing task's trace context to the observability
    layer, so a span opened anywhere inside a task body (object fetch,
    checkpoint write, user span) parents under the task's span without
    importing runtime state from observability (that import would be a
    cycle)."""
    async_ctx = _trace_var.get()
    if async_ctx:
        return async_ctx
    ctx = task_context
    if ctx.trace_id:
        return (ctx.trace_id, ctx.span_id or "")
    return None


observability.register_context_provider(_obs_context_provider)


class Node:
    """One (possibly virtual) host: resources + object store + worker pool."""

    def __init__(self, runtime: "Runtime", resources: ResourceSet,
                 node_id: Optional[NodeID] = None, labels: Optional[dict] = None):
        self.runtime = runtime
        self.node_id = node_id or NodeID.from_random()
        self.resources = NodeResources(resources)
        self.store = ObjectStore(self.node_id)
        self.labels = labels or {}
        self.alive = True
        self.draining = False  # lifecycle: still alive, shun new placement
        # Autoscaler hazard hint: likely to drain soon, last-choice
        # placement (see scheduler.NodeState.pending_drain).
        self.pending_drain = False
        self._pool = ThreadPoolExecutor(
            max_workers=_MAX_NODE_THREADS,
            thread_name_prefix=f"node-{self.node_id.hex()[:6]}")
        # Bundle carve-outs: (pg_id, bundle_index) -> NodeResources
        self.bundles: Dict[Tuple[PlacementGroupID, int], NodeResources] = {}

    def submit(self, fn: Callable, *args) -> None:
        self._pool.submit(fn, *args)

    def state(self) -> NodeState:
        return NodeState(self.node_id, self.resources, self.alive,
                         draining=self.draining,
                         pending_drain=self.pending_drain)

    def kill(self):
        """Simulate host failure: objects lost, resources gone (chaos tests)."""
        self.alive = False

    def shutdown(self):
        self.alive = False
        self._pool.shutdown(wait=False, cancel_futures=True)


class ActorState:
    RESTARTING = "RESTARTING"
    ALIVE = "ALIVE"
    DEAD = "DEAD"
    PENDING = "PENDING"

    def __init__(self, actor_id: ActorID, cls, args, kwargs, options,
                 name: Optional[str], namespace: str):
        self.actor_id = actor_id
        self.cls = cls
        self.args = args
        self.kwargs = kwargs
        self.options = options
        self.name = name
        self.namespace = namespace
        self.node_id: Optional[NodeID] = None
        self.instance: Any = None
        self.status = self.PENDING
        self.restart_count = 0
        self.mailbox: "queue.Queue" = queue.Queue()
        self.seq = 0
        self.lock = threading.RLock()
        self.ready = threading.Event()
        self.death_cause: Optional[BaseException] = None
        self.threads: List[threading.Thread] = []
        self.is_async = False
        self.loop = None  # asyncio loop for async actors
        self.devices: Optional[list] = None


class PlacementGroupState:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[ResourceSet],
                 strategy: str, name: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.bundle_nodes: Optional[List[NodeID]] = None
        self.ready = threading.Event()
        self.state = "PENDING"


class KVStore:
    """Internal KV with namespaces (GcsKvManager parity,
    ``python/ray/_private/gcs_utils.py:264-341``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[bytes, Dict[bytes, bytes]] = {}

    @staticmethod
    def _ns(namespace: Optional[bytes]) -> bytes:
        return namespace or b""

    def put(self, key: bytes, value: bytes, overwrite: bool = True,
            namespace: Optional[bytes] = None) -> bool:
        with self._lock:
            ns = self._data.setdefault(self._ns(namespace), {})
            if not overwrite and key in ns:
                return False
            ns[key] = value
            return True

    def get(self, key: bytes, namespace: Optional[bytes] = None) -> Optional[bytes]:
        with self._lock:
            return self._data.get(self._ns(namespace), {}).get(key)

    def delete(self, key: bytes, namespace: Optional[bytes] = None) -> bool:
        with self._lock:
            return self._data.get(self._ns(namespace), {}).pop(key, None) is not None

    def keys(self, prefix: bytes = b"", namespace: Optional[bytes] = None) -> List[bytes]:
        with self._lock:
            return [k for k in self._data.get(self._ns(namespace), {})
                    if k.startswith(prefix)]


class Runtime:
    """Cluster state service + task manager for this driver process."""

    def __init__(self, job_id: Optional[JobID] = None):
        self.job_id = job_id or JobID.from_random()
        self.nodes: Dict[NodeID, Node] = {}
        self._node_order: List[NodeID] = []  # raylint: guarded-by(self.lock)
        self.kv = KVStore()
        from ray_tpu._private.ids import _Counter
        self._put_counter = _Counter()
        self.reference_counter = ReferenceCounter(self._on_ref_zero)
        self.lock = threading.RLock()
        self.head_node: Optional[Node] = None

        # object directory: ObjectID -> NodeID (owner store)
        self.object_locations: Dict[ObjectID, NodeID] = {}  # raylint: guarded-by(self.lock)
        # Seal notifications: get()/wait() block here instead of polling;
        # every seal_return/seal_error wakes the waiters (the reference's
        # plasma object-ready notification path).
        self._seal_cv = threading.Condition()
        # lineage: ObjectID -> TaskSpec that produces it
        self.lineage: Dict[ObjectID, TaskSpec] = {}  # raylint: guarded-by(self.lock)
        self.task_states: Dict[TaskID, str] = {}  # raylint: guarded-by(self.lock)
        self.cancel_flags: Dict[TaskID, threading.Event] = {}  # raylint: guarded-by(self.lock)

        self.actors: Dict[ActorID, ActorState] = {}  # raylint: guarded-by(self.lock)
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}  # raylint: guarded-by(self.lock)
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupState] = {}  # raylint: guarded-by(self.lock)

        self.hybrid_policy = HybridPolicy()
        self.spread_policy = SpreadPolicy()

        # Shared retry pacing (see _private/backoff.py): task retries and
        # actor restarts take a jittered exponential delay from these
        # instead of a fixed task_retry_delay_ms sleep.
        self._retry_backoff = BackoffPolicy(
            base_s=_config.get("task_retry_delay_ms") / 1e3,
            max_s=_config.get("task_retry_max_delay_ms") / 1e3,
            deadline_s=0)

        # Pending queue of tasks waiting for resources / dependencies.
        self._pending: List[dict] = []  # raylint: guarded-by(self._pending_cv)
        # items the dispatcher is CURRENTLY iterating (it swaps _pending
        # to a local list per pass); admission depth checks must count
        # both, or the cap is porous exactly when the backlog is deepest
        self._dispatch_pass_n = 0
        self._pending_cv = threading.Condition()
        self._dispatch_mutex = threading.Lock()  # single-dispatcher guard
        self._inline_dispatch = bool(_config.get("inline_dispatch"))
        self._dispatch_dirty = False  # kick arrived while loop was busy
        # Per-task completion hooks, fired once when a task reaches a final
        # state (FINISHED/FAILED/CANCELLED, not retries). The host daemon
        # uses these to turn local completions into RPC replies; a task can
        # carry several hooks when a caller re-pushed an attempt it already
        # admitted (duplicate pushes attach instead of re-executing).
        self.completion_hooks: Dict[TaskID, List[Callable[[TaskSpec], None]]] = {}  # raylint: guarded-by(self.lock)
        # Infeasible requests get this long for the cluster view to change
        # (a node joining) before the error is sealed. 0 = fail fast; the
        # distributed runtime raises it because its view is refreshed
        # asynchronously and may trail reality by a refresh interval.
        self._infeasible_grace_s = 0.0
        self.autoscaling_enabled = False  # set by StandardAutoscaler
        self._events: List[dict] = []  # structured event log
        self._event_file = None
        self._event_file_lock = threading.Lock()
        self._shutdown = False
        self._util_pool = ThreadPoolExecutor(max_workers=32,
                                             thread_name_prefix="rt-util")
        try:
            self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                                name="rt-dispatcher",
                                                daemon=True)
            self._dispatcher.start()
        except Exception:
            # thread-limit failures must not strand the utility pool
            self._util_pool.shutdown(wait=False)
            raise

    # ------------------------------------------------------------------ nodes

    def add_node(self, resources: ResourceSet, labels: Optional[dict] = None) -> Node:
        node = Node(self, resources, labels=labels)
        with self.lock:
            self.nodes[node.node_id] = node  # raylint: allow(data-race) _sealed_locally deliberately probes nodes lock-free inside wait predicates; nodes are add-only
            self._node_order.append(node.node_id)
            if self.head_node is None:
                self.head_node = node  # raylint: allow(data-race) set once when the first node joins, before any task can be submitted
        self._kick()
        return node

    def remove_node(self, node_id: NodeID):
        """Node death: lose its objects, fail its actors, trigger recovery."""
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None:
                return
            node.kill()
            dead_actors = [a for a in self.actors.values()
                           if a.node_id == node_id and a.status != ActorState.DEAD]
            lost_objects = [oid for oid, nid in self.object_locations.items()
                            if nid == node_id]
        for a in dead_actors:
            self._handle_actor_failure(a, exc.NodeDiedError(
                f"node {node_id.hex()[:8]} died"))
        for oid in lost_objects:
            with self.lock:
                self.object_locations.pop(oid, None)
        self.emit_event("NODE_DEAD", node_id=node_id.hex())
        self._kick()

    def node_states(self) -> List[NodeState]:
        with self.lock:
            return [self.nodes[nid].state() for nid in self._node_order]

    def set_pending_drain(self, node_id_hex: str, flag: bool) -> None:
        """Autoscaler hazard hint: mark a node last-choice for placement
        (it stays fully schedulable — see NodeState.pending_drain)."""
        from ray_tpu._private.ids import NodeID
        with self.lock:
            node = self.nodes.get(NodeID(bytes.fromhex(node_id_hex)))
        if node is not None and node.pending_drain != flag:
            node.pending_drain = flag
            self._kick()

    # ---------------------------------------------------------------- objects

    def put_object(self, value: Any, owner_node: Optional[Node] = None) -> ObjectID:
        node = owner_node or self._current_or_head_node()
        from ray_tpu._private.worker import current_task_id
        tid = current_task_id()
        # Runtime-global counter: driver threads share the driver TaskID, so a
        # per-task counter would collide across threads.
        oid = ObjectID.for_put(tid, self._put_counter.next())
        node.store.put(oid, value)
        with self.lock:
            self.object_locations[oid] = node.node_id
        return oid

    def seal_return(self, oid: ObjectID, value: Any, node: Node):
        node.store.put(oid, value)
        with self.lock:
            self.object_locations[oid] = node.node_id
        self._notify_sealed()

    def seal_error(self, oid: ObjectID, error: BaseException, node: Node):
        node.store.put_error(oid, error)
        with self.lock:
            self.object_locations[oid] = node.node_id
        self._notify_sealed()

    def _notify_sealed(self):
        with self._seal_cv:
            self._seal_cv.notify_all()

    def _wait_for_seal(self, ready_pred, max_wait_s: float):
        """Block until ``ready_pred()`` or ``max_wait_s`` elapsed; wakes on
        seal notifications. The predicate is evaluated under the condvar
        (sealers notify under it too) so a seal landing between the
        caller's check and the wait is never lost, and unrelated seals
        don't end the wait early (the loop re-waits until the deadline).
        Predicates must be CHEAP and must NOT take self.lock (they run
        with the seal lock held; seal paths hold self.lock while
        notifying) — check stores directly."""
        deadline = time.monotonic() + max_wait_s
        with self._seal_cv:
            while not ready_pred():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._seal_cv.wait(remaining)

    def _sealed_locally(self, oid: ObjectID) -> bool:
        """Lock-free-ish readiness probe safe inside _wait_for_seal
        predicates: store containment only, no runtime lock, no RPCs."""
        for node in list(self.nodes.values()):
            if node.alive and node.store.contains(oid):
                return True
        return False

    def get_object(self, oid: ObjectID, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            read_failed = False  # located copy was unreadable this pass
            node = self._locate(oid)
            if node is not None:
                try:
                    remaining = None if deadline is None else max(
                        0.0, deadline - time.monotonic())
                    return node.store.get(oid, timeout=remaining)
                except exc.RayTpuError:
                    raise
                except TimeoutError:
                    raise exc.GetTimeoutError(f"get({oid}) timed out")
                except Exception as e:
                    from ray_tpu._private.object_store import ObjectLostError
                    if not isinstance(e, ObjectLostError):
                        raise
                    read_failed = True
            # No live copy. Producing task may still be in flight (just wait),
            # or it finished and the copy was lost (reconstruct from lineage).
            with self.lock:
                spec = self.lineage.get(oid)
                state = (self.task_states.get(spec.task_id)
                         if spec is not None else None)
            if spec is None:
                raise exc.ObjectLostError(
                    f"object {oid} is lost and has no lineage to reconstruct")
            if state in ("FINISHED", "FAILED", None):
                if not read_failed and self._locate(oid) is not None:
                    continue  # sealed between the locate above and here
                # The value (or error) existed and was lost with its node.
                if not self._try_reconstruct(oid):
                    raise exc.ObjectLostError(
                        f"object {oid} is lost and could not be reconstructed")
            if deadline is not None and time.monotonic() > deadline:
                raise exc.GetTimeoutError(f"get({oid}) timed out")
            self._wait_for_seal(lambda: self._sealed_locally(oid), 0.05)

    # Overlapping blocking gets only pays off when resolution can involve
    # the wire (remote fetches / pushed-task waits); the in-process runtime
    # resolves everything off local seal events, where extra waiter threads
    # are pure condvar-wakeup overhead.
    _concurrent_get = False

    def get_objects(self, oids: Sequence[ObjectID],
                    timeout: Optional[float] = None) -> list:
        """Batch get preserving input order under ONE shared deadline.
        Locally-sealed ids take the plain sequential read; on runtimes
        flagged ``_concurrent_get`` the rest resolve concurrently, so N
        remote pulls (striped fetches, distinct owners) overlap instead of
        serializing N round trips. Errors surface in input order, exactly
        as the sequential loop would raise them."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)

        def _remaining():
            return (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))

        values: Dict[ObjectID, Any] = {}
        errors: Dict[ObjectID, BaseException] = {}
        if self._concurrent_get:
            slow = [o for o in dict.fromkeys(oids)
                    if not self._sealed_locally(o)]
            if len(slow) > 1:
                with ThreadPoolExecutor(
                        max_workers=min(8, len(slow)),
                        thread_name_prefix="obj-get") as pool:
                    futs = [(o, pool.submit(self.get_object, o, _remaining()))
                            for o in slow]
                    for o, f in futs:
                        try:
                            # each worker runs get_object(_remaining()):
                            # the shared deadline is enforced inside the
                            # call, so this result() is bounded by it
                            # raylint: allow(deadline-drop) bounded in callee
                            values[o] = f.result()
                        except BaseException as e:  # noqa: BLE001 — replayed
                            errors[o] = e           # in input order below
        out = []
        for o in oids:
            if o in errors:
                raise errors[o]
            if o not in values:
                values[o] = self.get_object(o, timeout=_remaining())
            out.append(values[o])
        return out

    def object_ready(self, oid: ObjectID) -> bool:
        node = self._locate(oid)
        return node is not None and node.store.contains(oid)

    def _locate(self, oid: ObjectID) -> Optional[Node]:
        with self.lock:
            nid = self.object_locations.get(oid)
            if nid is None:
                return None
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                return None
            return node

    def _try_reconstruct(self, oid: ObjectID) -> bool:
        """Lineage reconstruction (ObjectRecoveryManager::RecoverObject)."""
        with self.lock:
            spec = self.lineage.get(oid)
            if spec is None:
                return False
            state = self.task_states.get(spec.task_id)
            if state == "RESUBMITTED":
                return True
            if spec.retries_left() <= 0 and state != "PENDING":
                return False
            self.task_states[spec.task_id] = "RESUBMITTED"
            spec.attempt += 1
        self.emit_event("OBJECT_RECONSTRUCT", object_id=oid.hex(),
                        task=spec.function_name)
        # Elastic recovery: a hard node-affinity to a dead node would make the
        # lineage permanently unrecoverable; degrade to soft affinity.
        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
        strat = spec.options.scheduling_strategy
        if isinstance(strat, NodeAffinitySchedulingStrategy) and not strat.soft:
            with self.lock:
                target_alive = any(
                    n.node_id.hex() == strat.node_id and n.alive
                    for n in (self.nodes[nid] for nid in self._node_order))
            if not target_alive:
                spec.options.scheduling_strategy = NodeAffinitySchedulingStrategy(
                    node_id=strat.node_id, soft=True)
        if spec.is_actor_task():
            self.submit_actor_task(spec.actor_id, spec)
        else:
            self.submit_task(spec)
        return True

    def _on_ref_zero(self, oid: ObjectID):
        node = self._locate(oid)
        if node is not None:
            node.store.free(oid)
        with self.lock:
            self.object_locations.pop(oid, None)
            self.lineage.pop(oid, None)

    # ------------------------------------------------------------------ tasks

    def _attach_trace(self, spec: TaskSpec):
        """Propagate the submitting span's trace context into the spec
        (tracing_helper.py:160-175 role): children inherit the trace id
        with the current span as parent; a root submission mints a fresh
        trace id when profiling is on (tracing is free when it's off)."""
        if spec.trace_id:
            return  # retries keep their original identity
        async_ctx = _trace_var.get()
        ctx = task_context
        if async_ctx:
            spec.trace_id, spec.parent_span_id = async_ctx
        elif ctx.trace_id:
            spec.trace_id = ctx.trace_id
            spec.parent_span_id = ctx.span_id
        else:
            obs_ctx = (observability.current()
                       if observability.ENABLED else None)
            if obs_ctx:  # explicit span (serve request, user span(...))
                spec.trace_id, spec.parent_span_id = obs_ctx
            elif _prof().enabled:
                spec.trace_id = os.urandom(8).hex()

    def submit_task(self, spec: TaskSpec) -> List[ObjectID]:
        self._attach_trace(spec)
        if perf.ENABLED and not spec.perf_submit_s:
            spec.perf_submit_s = time.time()
        if not spec.return_ids:
            spec.return_ids = tuple(
                ObjectID.for_return(spec.task_id, i)
                for i in range(spec.options.num_returns))
        with self.lock:
            for rid in spec.return_ids:
                self.lineage[rid] = spec
            self.task_states[spec.task_id] = "PENDING"
            cancel = self.cancel_flags.setdefault(spec.task_id, threading.Event())  # raylint: guarded-by(self.lock)
        # Pin argument objects for the duration of the task.
        refs = _ref_ids_in(spec.args, spec.kwargs)
        for oid in refs:
            self.reference_counter.pin_for_task(oid)
        item = {"spec": spec, "cancel": cancel}
        # Inline fast path: a ref-free task whose dispatch decision is
        # immediate skips the queue + dispatcher-thread hop (two context
        # switches per task — the dominant per-task cost at high rates on
        # busy hosts). The dispatch mutex preserves the single-dispatcher
        # invariant (allocation math is not self-synchronized); tasks
        # with ref deps keep the queue path so dependency probes never
        # run on the submitter's thread.
        if not refs and self._inline_dispatch and self._dispatch_now(item):
            return list(spec.return_ids)
        with self._pending_cv:
            self._pending.append(item)
            self._pending_cv.notify_all()
        return list(spec.return_ids)

    def _dispatch_now(self, item: dict) -> bool:
        # A free mutex is NOT enough: a non-empty backlog means older
        # tasks are parked awaiting capacity, and inlining a newcomer
        # would let it jump the queue (and under a sustained stream,
        # starve the backlog).
        with self._pending_cv:
            if self._pending or self._dispatch_pass_n:
                return False
        if not self._dispatch_mutex.acquire(blocking=False):
            return False  # dispatcher mid-pass: just queue
        try:
            action = self._try_dispatch(item)
            self._flush_dispatch_batches()  # inline path has no pass end
        except Exception:  # raylint: allow(swallow) Infeasible & friends: the queue path re-runs the policy
            return False   # error handling — re-run it there
        finally:
            self._dispatch_mutex.release()
        return action == "done"

    def cancel_task(self, task_id: TaskID, force: bool = False):
        with self.lock:
            flag = self.cancel_flags.get(task_id)
            state = self.task_states.get(task_id)
        if flag is not None:
            flag.set()
        self._kick()

    # The dispatcher: dependency resolution + scheduling + admission.
    def _dispatch_loop(self):
        while not self._shutdown:
            with self._pending_cv:
                if not self._pending:
                    self._pending_cv.wait(timeout=0.05)
                pending, self._pending = self._pending, []
                self._dispatch_pass_n = len(pending)  # raylint: guarded-by(self._pending_cv)
            still_waiting = []
            for item in pending:
                try:
                    with self._dispatch_mutex:
                        action = self._try_dispatch(item)
                except Infeasible as e:
                    if self.autoscaling_enabled:
                        # The cluster can grow: keep infeasible tasks
                        # queued as autoscaler demand (reference: pending
                        # infeasible tasks feed resource_demand_scheduler).
                        still_waiting.append(item)
                        continue
                    if self._infeasible_grace_s > 0:
                        since = item.setdefault("infeasible_since",
                                                time.monotonic())
                        if time.monotonic() - since < self._infeasible_grace_s:
                            still_waiting.append(item)
                            continue
                    spec = item["spec"]
                    err_cls = (exc.PlacementGroupSchedulingError
                               if spec.options.placement_group is not None
                               else exc.RayTpuError)
                    for rid in spec.return_ids:
                        self.seal_error(rid, err_cls(str(e)), self.head_node)
                    self._unpin_args(spec)
                    with self.lock:
                        self.task_states[spec.task_id] = "FAILED"
                    self._fire_completion(spec)
                    continue
                except Exception as e:  # defensive: never kill the dispatcher
                    spec = item["spec"]
                    logger.exception("dispatch error for %s", spec.function_name)
                    for rid in spec.return_ids:
                        self.seal_error(rid, exc.RayTpuError(
                            f"scheduling failed: {e}"), self.head_node)
                    self._unpin_args(spec)
                    with self.lock:
                        self.task_states[spec.task_id] = "FAILED"
                    self._fire_completion(spec)
                    continue
                if action == "wait":
                    still_waiting.append(item)
            # Batched remote pushes accumulate during the pass; ship them
            # as one frame per daemon (no-op for the in-process runtime).
            try:
                self._flush_dispatch_batches()
            except Exception:  # defensive: never kill the dispatcher
                logger.exception("dispatch batch flush failed")
            if still_waiting:
                with self._pending_cv:
                    self._pending.extend(still_waiting)
                    self._dispatch_pass_n = 0
                    # Event-driven backoff: a seal/submit kick wakes the
                    # loop immediately instead of paying a fixed sleep per
                    # dependency-chain hop; the dirty flag covers kicks
                    # that raced with this pass (lost-wakeup).
                    if not self._dispatch_dirty:
                        self._pending_cv.wait(timeout=0.02)
                    self._dispatch_dirty = False
            else:
                with self._pending_cv:
                    self._dispatch_pass_n = 0

    def _flush_dispatch_batches(self):
        """Hook: distributed runtimes flush per-daemon push batches."""

    def _kick(self):
        with self._pending_cv:
            self._dispatch_dirty = True
            self._pending_cv.notify_all()

    def _deps_ready(self, spec: TaskSpec) -> bool:
        for oid in _ref_ids_in(spec.args, spec.kwargs):
            if not self.object_ready(oid):
                node = self._locate(oid)
                if node is None:
                    # Reconstruct ONLY if the producing task already ran
                    # (value existed and was lost with its node). While the
                    # producer is merely pending/running, resubmitting it
                    # here would duplicate it on every dispatcher pass — a
                    # task storm that grows combinatorially on dependency
                    # chains.
                    with self.lock:
                        known = oid in self.object_locations
                        dep_spec = self.lineage.get(oid)
                        state = (self.task_states.get(dep_spec.task_id)
                                 if dep_spec is not None else None)
                    if (not known and dep_spec is not None
                            and state in ("FINISHED", "FAILED")):
                        self._try_reconstruct(oid)
                return False
        return True

    def _try_dispatch(self, item: dict) -> str:
        spec: TaskSpec = item["spec"]
        cancel: threading.Event = item["cancel"]
        if cancel.is_set():
            for rid in spec.return_ids:
                self.seal_error(rid, exc.TaskCancelledError(spec.task_id),
                                self.head_node)
            self._unpin_args(spec)
            with self.lock:
                self.task_states[spec.task_id] = "CANCELLED"
            self._fire_completion(spec)
            return "done"
        if not self._deps_ready(spec):
            return "wait"
        # Check a dep didn't resolve to an error (error propagation).
        err = self._first_dep_error(spec)
        if err is not None:
            for rid in spec.return_ids:
                self.seal_error(rid, err, self.head_node)
            self._unpin_args(spec)
            with self.lock:
                self.task_states[spec.task_id] = "FAILED"
            self._fire_completion(spec)
            return "done"
        node_id = self._select_node(spec)
        if node_id is None:
            return "wait"
        node = self.nodes[node_id]
        request = self._effective_request(spec)
        alloc_target = self._allocation_target(spec, node)
        if not alloc_target.can_fit(request):
            return "wait"
        alloc_target.allocate(request)
        with self.lock:
            self.task_states[spec.task_id] = "RUNNING"
        node.submit(self._execute_task, spec, node, request, alloc_target, cancel)
        return "done"

    def _first_dep_error(self, spec: TaskSpec) -> Optional[BaseException]:
        for oid in _ref_ids_in(spec.args, spec.kwargs):
            node = self._locate(oid)
            if node is None:
                continue
            err = node.store.peek_error(oid)
            if isinstance(err, (exc.TaskError, exc.TaskCancelledError,
                                exc.ActorDiedError)):
                return err
        return None

    def _effective_request(self, spec: TaskSpec) -> ResourceSet:
        return spec.options.resources

    def _allocation_target(self, spec: TaskSpec, node: Node):
        pg = spec.options.placement_group
        if pg is not None:
            # NOTE: resolved via node.bundles only — an executing daemon
            # holds the reserved bundles but NOT the creator's
            # placement_groups table, and release paths must work there.
            idx = spec.options.placement_group_bundle_index
            if idx < 0:
                # Any bundle on this node with room.
                for (pgid, i), br in node.bundles.items():
                    if pgid == pg.id and br.can_fit(spec.options.resources):
                        return br
                # fall through: first bundle on node
                for (pgid, i), br in node.bundles.items():
                    if pgid == pg.id:
                        return br
                raise Infeasible("no bundle of placement group on chosen node")
            return node.bundles[(pg.id, idx)]
        return node.resources

    def _select_node(self, spec: TaskSpec) -> Optional[NodeID]:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)
        strategy = spec.options.scheduling_strategy
        request = spec.options.resources
        states = self.node_states()
        pg = spec.options.placement_group
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg = strategy.placement_group
            spec.options.placement_group = pg
            spec.options.placement_group_bundle_index = (
                strategy.placement_group_bundle_index)
        if pg is not None:
            with self.lock:
                pg_state = self.placement_groups[pg.id]
            if not pg_state.ready.is_set():
                return None
            idx = spec.options.placement_group_bundle_index
            candidates = (pg_state.bundle_nodes if idx < 0
                          else [pg_state.bundle_nodes[idx]])
            for nid in candidates:
                node = self.nodes[nid]
                if not node.alive:
                    continue
                for (pgid, i), br in node.bundles.items():
                    if pgid == pg.id and br.can_fit(request):
                        return nid
            return None
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            from ray_tpu._private.scheduler import NodeAffinityPolicy
            return NodeAffinityPolicy().select(states, request,
                                               strategy.node_id, strategy.soft)
        if strategy == "SPREAD":
            chosen = self.spread_policy.select(states, request)
        else:
            preferred = task_context.node_id
            chosen = self.hybrid_policy.select(states, request, preferred)
        if chosen is None and not any(
                n.alive and n.resources.could_ever_fit(request)
                for n in states):
            raise Infeasible(
                f"request {request} cannot be satisfied by any node "
                f"(cluster totals: "
                f"{[n.resources.total.to_dict() for n in states]})")
        return chosen

    def _assign_devices(self, request: ResourceSet, node: Node) -> Optional[list]:
        """Map a TPU resource grant to concrete jax devices (the TPU-native
        analogue of CUDA_VISIBLE_DEVICES assignment, ``_raylet.pyx:563``)."""
        n = int(request.get(TPU))
        if n <= 0:
            return None
        try:
            import jax
            devs = jax.devices()
        except Exception:  # raylint: allow(swallow) capability probe: no jax backend
            return None
        return devs[:n] if len(devs) >= n else devs

    def _execute_task(self, spec: TaskSpec, node: Node, request: ResourceSet,
                      alloc_target, cancel: threading.Event):
        ctx = task_context
        prev = (ctx.node_id, ctx.task_id, ctx.job_id, ctx.put_counter,
                ctx.devices, ctx.cancel_flag, ctx.placement_group,
                ctx.trace_id, ctx.span_id)
        ctx.node_id = node.node_id
        ctx.task_id = spec.task_id
        ctx.job_id = spec.job_id
        ctx.put_counter = 0
        ctx.devices = self._assign_devices(request, node)
        ctx.cancel_flag = cancel
        ctx.placement_group = spec.options.placement_group
        # Trace context for this span: children submitted by the task
        # body inherit (trace_id, span_id) via _attach_trace.
        ctx.trace_id = spec.trace_id
        span_id = os.urandom(8).hex() if spec.trace_id else ""
        ctx.span_id = span_id
        if _flight.ENABLED:
            # flight recorder: a hard-killed process's bundle names what
            # was RUNNING (and which trace it belonged to) when it died
            _flight.task_started(spec.task_id.hex(), spec.function_name,
                                 trace_id=spec.trace_id, span_id=span_id)
        t0 = time.monotonic()
        try:
            if cancel.is_set():
                raise exc.TaskCancelledError(spec.task_id)
            if chaos.ENABLED:
                # delay stalls the worker; error fails the task (retryable
                # per retry_exceptions); exit kills this PROCESS mid-task —
                # the injected host-loss scenario resubmission must survive
                chaos.inject("task.execute", task=spec.task_id.hex()[:8],
                             name=spec.function_name)
            args = _resolve_refs(spec.args, self)
            kwargs = _resolve_refs(spec.kwargs, self)
            env = _materialize_env(spec)
            if env is not None:
                with env.applied():
                    result = spec.function(*args, **kwargs)
            else:
                result = spec.function(*args, **kwargs)
            if cancel.is_set():
                raise exc.TaskCancelledError(spec.task_id)
            self._seal_results(spec, node, result)
            with self.lock:
                self.task_states[spec.task_id] = "FINISHED"
        except BaseException as e:  # noqa: BLE001
            self._handle_task_failure(spec, node, e)
        finally:
            if _flight.ENABLED:
                _flight.task_finished(spec.task_id.hex())
            alloc_target.release(request)
            self._unpin_args(spec)
            dur = time.monotonic() - t0
            if perf.ENABLED:
                perf.observe("task.execute", dur * 1e3)
                if spec.perf_submit_s:
                    # Cross-host stamps are rebased onto this clock via
                    # clocksync (heartbeat-beacon offset), so the delta is
                    # already skew-corrected; residual error is bounded by
                    # the heartbeat RTTs. Clamp instead of discard: a
                    # stamp that still lands inside the execution window
                    # means ~zero scheduling wait, not a bogus sample.
                    e2e = max(time.time() - spec.perf_submit_s, dur)
                    perf.observe("task.e2e", e2e * 1e3)
                    perf.observe("task.sched", (e2e - dur) * 1e3)
            self.emit_event("TASK_DONE", task=spec.function_name,
                            ms=round(dur * 1e3, 3))
            span_args = {"task_id": spec.task_id.hex()}
            if spec.trace_id:
                span_args.update(trace_id=spec.trace_id, span_id=span_id,
                                 parent_span_id=spec.parent_span_id)
            _prof().record(spec.function_name, "task",
                           pid=f"node:{node.node_id.hex()[:8]}",
                           start_s=time.time() - dur, dur_s=dur,
                           args=span_args)
            (ctx.node_id, ctx.task_id, ctx.job_id, ctx.put_counter,
             ctx.devices, ctx.cancel_flag, ctx.placement_group,
             ctx.trace_id, ctx.span_id) = prev
            self._fire_completion(spec)
            self._kick()

    def _seal_results(self, spec: TaskSpec, node: Node, result: Any):
        n = spec.options.num_returns
        if n == 1:
            self.seal_return(spec.return_ids[0], result, node)
        elif n == 0:
            pass
        else:
            values = tuple(result)
            if len(values) != n:
                raise ValueError(
                    f"task {spec.function_name} declared num_returns={n} "
                    f"but returned {len(values)} values")
            for rid, v in zip(spec.return_ids, values):
                self.seal_return(rid, v, node)

    def _handle_task_failure(self, spec: TaskSpec, node: Node, e: BaseException):
        if isinstance(e, exc.TaskCancelledError):
            for rid in spec.return_ids:
                self.seal_error(rid, e, node)
            with self.lock:
                self.task_states[spec.task_id] = "CANCELLED"
            return
        if spec.should_retry(e):
            spec.attempt += 1
            # jittered exponential via the shared policy: simultaneous
            # failures (a died dependency, an OOM kill) don't retry in
            # lockstep
            delay = self._retry_backoff.delay_for(spec.attempt - 1)
            self.emit_event("TASK_RETRY", task=spec.function_name,
                            attempt=spec.attempt)
            timer = threading.Timer(delay, lambda: self.submit_task(spec))
            timer.daemon = True
            timer.start()
            return
        wrapped = e if isinstance(e, exc.RayTpuError) else exc.TaskError(
            spec.function_name, e)
        for rid in spec.return_ids:
            self.seal_error(rid, wrapped, node)
        with self.lock:
            self.task_states[spec.task_id] = "FAILED"

    def _unpin_args(self, spec: TaskSpec):
        for oid in _ref_ids_in(spec.args, spec.kwargs):
            self.reference_counter.unpin_for_task(oid)

    def _fire_completion(self, spec: TaskSpec):
        """Invoke the task's completion hooks iff it reached a final state."""
        with self.lock:
            state = self.task_states.get(spec.task_id)
            if state not in ("FINISHED", "FAILED", "CANCELLED"):
                return
            hooks = self.completion_hooks.pop(spec.task_id, None) or []  # raylint: guarded-by(self.lock)
        for hook in hooks:
            try:
                hook(spec)
            except Exception:
                logger.exception("completion hook failed for %s",
                                 spec.function_name)

    def reduce_ref(self, oid: ObjectID):
        """Pickle-reduction for an ObjectRef owned by this runtime.
        In-process semantics: pin until the deserializer re-binds
        (see ObjectRef.__reduce__); the distributed runtime overrides this
        with the cross-process borrowing protocol."""
        from ray_tpu.object_ref import _deserialize_borrowed_ref
        self.reference_counter.pin_for_task(oid)
        return (_deserialize_borrowed_ref, (oid.binary(),))

    def _current_or_head_node(self) -> Node:
        nid = task_context.node_id
        with self.lock:
            if nid is not None and nid in self.nodes and self.nodes[nid].alive:
                return self.nodes[nid]
            assert self.head_node is not None, "runtime has no nodes"
            return self.head_node

    # ----------------------------------------------------------------- actors

    def create_actor(self, state: ActorState) -> None:
        with self.lock:
            self.actors[state.actor_id] = state
            if state.name:
                key = (state.namespace, state.name)
                if key in self.named_actors:
                    raise ValueError(
                        f"actor name {state.name!r} already taken in "
                        f"namespace {state.namespace!r}")
                self.named_actors[key] = state.actor_id
        self._util_pool.submit(self._place_and_start_actor, state)

    def _restore_drained_actor(self, state: ActorState):
        """Hook for the distributed runtime: return a live instance to
        resume a restarting actor from a drained node's snapshot, or None
        to construct it normally. The in-process runtime has no drain
        lifecycle, so there is never a snapshot to resume from."""
        return None

    def _place_and_start_actor(self, state: ActorState, restart: bool = False):
        deadline = time.monotonic() + _config.get("worker_lease_timeout_s")
        pause = BackoffPolicy(base_s=0.005, max_s=0.05, deadline_s=0,
                              jitter=False)
        attempt = 0
        request = state.options.resources
        spec_like = TaskSpec(
            task_id=TaskID.for_actor_task(self.job_id, state.actor_id),
            job_id=self.job_id, function=lambda: None,
            function_name=f"{state.cls.__name__}.__init__", args=state.args,
            kwargs=state.kwargs, options=state.options)
        while True:
            try:
                node_id = self._select_node(spec_like)
            except Infeasible as e:
                self._mark_actor_dead(state, exc.ActorDiedError(str(e)))
                return
            if node_id is not None:
                node = self.nodes[node_id]
                target = self._allocation_target(spec_like, node)
                if target.can_fit(request):
                    target.allocate(request)
                    break
            if time.monotonic() > deadline:
                self._mark_actor_dead(state, exc.ActorDiedError(
                    f"could not place actor {state.cls.__name__} "
                    f"(resources {request})"))
                return
            time.sleep(pause.delay_for(attempt))
            attempt += 1
        state.node_id = node_id
        state.devices = self._assign_devices(request, node)
        self._start_actor_on_node(state, node, request)

    def _start_actor_on_node(self, state: ActorState, node: Node,
                             request: ResourceSet):
        import inspect
        methods = [m for _, m in inspect.getmembers(
            state.cls, predicate=inspect.isfunction)]
        state.is_async = any(inspect.iscoroutinefunction(m) for m in methods)
        max_c = getattr(state.options, "max_concurrency", None) or 1
        if state.is_async and max_c == 1:
            max_c = 1000  # reference default for async actors

        def _init_and_loop():
            ctx = task_context
            ctx.node_id = node.node_id
            ctx.actor_id = state.actor_id
            ctx.job_id = self.job_id
            ctx.devices = state.devices
            ctx.placement_group = state.options.placement_group
            try:
                restored = self._restore_drained_actor(state)
                if restored is not None:
                    # Previous host drained gracefully: resume from its
                    # snapshot instead of re-running __init__.
                    state.instance = restored
                else:
                    args = _resolve_refs(state.args, self)
                    kwargs = _resolve_refs(state.kwargs, self)
                    env = _materialize_env_for_actor(state)
                    if env is not None:
                        with env.applied():
                            state.instance = state.cls(*args, **kwargs)
                    else:
                        state.instance = state.cls(*args, **kwargs)
                state.status = ActorState.ALIVE
                state.ready.set()
                self.emit_event("ACTOR_ALIVE", actor=state.cls.__name__)
            except BaseException as e:  # noqa: BLE001
                self._mark_actor_dead(state, exc.ActorDiedError(
                    f"actor {state.cls.__name__} __init__ failed: "
                    f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))
                return
            if state.is_async:
                self._run_async_actor_loop(state, max_c)
            else:
                self._run_actor_loop(state, node)

        if state.is_async or max_c == 1:
            t = threading.Thread(target=_init_and_loop, daemon=True,
                                 name=f"actor-{state.cls.__name__}")
            state.threads = [t]
            t.start()
        else:
            # Threaded actor (max_concurrency>1): one mailbox, N consumers —
            # execution order is relaxed like the reference's
            # out_of_order_actor_scheduling_queue.cc.
            def _consumer_entry(first: bool):
                if first:
                    _init_and_loop()
                else:
                    state.ready.wait()
                    if state.status == ActorState.ALIVE:
                        ctx = task_context
                        ctx.node_id = node.node_id
                        ctx.actor_id = state.actor_id
                        ctx.job_id = self.job_id
                        ctx.devices = state.devices
                        self._run_actor_loop(state, node)
            state.threads = []
            for i in range(max_c):
                t = threading.Thread(target=_consumer_entry, args=(i == 0,),
                                     daemon=True,
                                     name=f"actor-{state.cls.__name__}-{i}")
                state.threads.append(t)
                t.start()

    def _run_actor_loop(self, state: ActorState, node: Node):
        while True:
            item = state.mailbox.get()
            if item is None or state.status == ActorState.DEAD:
                return
            spec, cancel = item
            ctx = task_context
            ctx.task_id = spec.task_id
            ctx.cancel_flag = cancel
            ctx.put_counter = 0
            ctx.trace_id = spec.trace_id
            span_id = os.urandom(8).hex() if spec.trace_id else ""
            ctx.span_id = span_id
            t0 = time.monotonic()
            try:
                if cancel.is_set():
                    raise exc.TaskCancelledError(spec.task_id)
                args = _resolve_refs(spec.args, self)
                kwargs = _resolve_refs(spec.kwargs, self)
                method = getattr(state.instance, spec.method_name)
                env = _materialize_env(spec, state)
                if env is not None:
                    with env.applied():
                        result = method(*args, **kwargs)
                else:
                    result = method(*args, **kwargs)
                self._seal_results(spec, node, result)
                with self.lock:
                    self.task_states[spec.task_id] = "FINISHED"
            except BaseException as e:  # noqa: BLE001
                # Runtime errors (incl. TaskCancelledError and serve's
                # overload/shed signals) re-raise RAW at get(), same as
                # the plain-task and async-actor paths — callers
                # discriminate on the type; user errors get the TaskError
                # wrapper naming the method.
                wrapped = (e if isinstance(e, exc.RayTpuError)
                           else exc.TaskError(
                               f"{state.cls.__name__}.{spec.method_name}", e))
                for rid in spec.return_ids:
                    self.seal_error(rid, wrapped, node)
                with self.lock:
                    self.task_states[spec.task_id] = "FAILED"
            finally:
                self._unpin_args(spec)
                dur = time.monotonic() - t0
                span_args = {"actor_id": state.actor_id.hex()}
                if spec.trace_id:
                    span_args.update(trace_id=spec.trace_id,
                                     span_id=span_id,
                                     parent_span_id=spec.parent_span_id)
                _prof().record(
                    f"{state.cls.__name__}.{spec.method_name}",
                    "actor_task", pid=f"node:{node.node_id.hex()[:8]}",
                    start_s=time.time() - dur, dur_s=dur,
                    args=span_args)
                self._fire_completion(spec)
                self._kick()

    def _run_async_actor_loop(self, state: ActorState, max_concurrency: int):
        import asyncio
        loop = asyncio.new_event_loop()
        state.loop = loop
        node = self.nodes[state.node_id]
        sem = asyncio.Semaphore(max_concurrency)

        async def _run_one(spec: TaskSpec, cancel):
            async with sem:
                span_id = os.urandom(8).hex() if spec.trace_id else ""
                token = (_trace_var.set((spec.trace_id, span_id))
                         if spec.trace_id else None)
                t0 = time.monotonic()
                try:
                    if cancel.is_set():
                        raise exc.TaskCancelledError(spec.task_id)
                    args = _resolve_refs(spec.args, self)
                    kwargs = _resolve_refs(spec.kwargs, self)
                    method = getattr(state.instance, spec.method_name)
                    env = _materialize_env(spec, state)
                    if env is not None:
                        with env.applied():
                            result = method(*args, **kwargs)
                    else:
                        result = method(*args, **kwargs)
                    if asyncio.iscoroutine(result):
                        result = await result
                    self._seal_results(spec, node, result)
                    with self.lock:
                        self.task_states[spec.task_id] = "FINISHED"
                except BaseException as e:  # noqa: BLE001
                    wrapped = e if isinstance(e, exc.RayTpuError) else exc.TaskError(
                        f"{state.cls.__name__}.{spec.method_name}", e)
                    for rid in spec.return_ids:
                        self.seal_error(rid, wrapped, node)
                    with self.lock:
                        self.task_states[spec.task_id] = "FAILED"
                finally:
                    if token is not None:
                        _trace_var.reset(token)
                    self._unpin_args(spec)
                    dur = time.monotonic() - t0
                    span_args = {"actor_id": state.actor_id.hex()}
                    if spec.trace_id:
                        span_args.update(
                            trace_id=spec.trace_id, span_id=span_id,
                            parent_span_id=spec.parent_span_id)
                    _prof().record(
                        f"{state.cls.__name__}.{spec.method_name}",
                        "actor_task",
                        pid=f"node:{node.node_id.hex()[:8]}",
                        start_s=time.time() - dur, dur_s=dur,
                        args=span_args)
                    self._fire_completion(spec)
                    self._kick()

        async def _pump():
            while state.status != ActorState.DEAD:
                item = await loop.run_in_executor(None, state.mailbox.get)
                if item is None:
                    break
                spec, cancel = item
                loop.create_task(_run_one(spec, cancel))

        try:
            loop.run_until_complete(_pump())
        finally:
            loop.close()

    def submit_actor_task(self, actor_id: ActorID, spec: TaskSpec) -> List[ObjectID]:
        self._attach_trace(spec)
        with self.lock:
            state = self.actors.get(actor_id)
        if not spec.return_ids:
            spec.return_ids = tuple(ObjectID.for_return(spec.task_id, i)
                                    for i in range(spec.options.num_returns))
        cancel = threading.Event()
        with self.lock:
            self.cancel_flags[spec.task_id] = cancel
            for rid in spec.return_ids:
                self.lineage[rid] = spec
            self.task_states[spec.task_id] = "PENDING"
        if state is None or state.status == ActorState.DEAD:
            cause = state.death_cause if state else None
            err = exc.ActorDiedError(f"actor {actor_id} is dead: {cause}")
            for rid in spec.return_ids:
                self.seal_error(rid, err, self._current_or_head_node())
            with self.lock:
                self.task_states[spec.task_id] = "FAILED"
            self._fire_completion(spec)
            return list(spec.return_ids)
        for oid in _ref_ids_in(spec.args, spec.kwargs):
            self.reference_counter.pin_for_task(oid)
        state.mailbox.put((spec, cancel))
        return list(spec.return_ids)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        with self.lock:
            state = self.actors.get(actor_id)
        if state is None:
            return
        max_restarts = getattr(state.options, "max_restarts", 0)
        out_of_restarts = (max_restarts != -1
                           and state.restart_count >= max_restarts)
        if no_restart or out_of_restarts:
            self._mark_actor_dead(state, exc.ActorDiedError(
                "actor was killed via ray_tpu.kill"))
        else:
            self._handle_actor_failure(state, exc.ActorDiedError("killed"))

    def _mark_actor_dead(self, state: ActorState, cause: BaseException):
        with state.lock:
            if state.status == ActorState.DEAD:
                return
            state.status = ActorState.DEAD
            state.death_cause = cause
            state.ready.set()
        # Fail everything still queued.
        drained = []
        try:
            while True:
                item = state.mailbox.get_nowait()
                if item is not None:
                    drained.append(item)
        except queue.Empty:
            pass
        node = self._current_or_head_node()
        for spec, _cancel in drained:
            for rid in spec.return_ids:
                self.seal_error(rid, exc.ActorDiedError(str(cause)), node)
            self._unpin_args(spec)
            with self.lock:
                self.task_states[spec.task_id] = "FAILED"
            self._fire_completion(spec)
        state.mailbox.put(None)  # wake consumers so threads exit
        self._release_actor_allocation(state)
        with self.lock:
            if state.name and self.named_actors.get(
                    (state.namespace, state.name)) == state.actor_id:
                del self.named_actors[(state.namespace, state.name)]
        self.emit_event("ACTOR_DEAD", actor=state.cls.__name__, cause=str(cause))

    def _release_actor_allocation(self, state: ActorState):
        """Release the dead/restarting incarnation's resource grant (once)."""
        with state.lock:
            node_id, state.node_id = state.node_id, None
        if node_id is None:
            return
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return
        try:
            target = self._allocation_target(
                TaskSpec(task_id=TaskID.for_task(self.job_id),
                         job_id=self.job_id, function=lambda: None,
                         function_name="", args=(), kwargs={},
                         options=state.options), node)
            target.release(state.options.resources)
        except Exception as e:
            logger.debug("resource release after actor death failed: %s", e)

    def _handle_actor_failure(self, state: ActorState, cause: BaseException):
        """Restart up to max_restarts (GcsActorManager::ReconstructActor)."""
        max_restarts = getattr(state.options, "max_restarts", 0)
        if max_restarts != -1 and state.restart_count >= max_restarts:
            self._mark_actor_dead(state, cause)
            return
        self._release_actor_allocation(state)
        with state.lock:
            state.restart_count += 1
            state.status = ActorState.RESTARTING
            state.ready.clear()
            state.instance = None
            # Hand queued work to the restarted incarnation and poison the old
            # mailbox so consumers on the failed node stop (the reference
            # replays in-flight actor tasks under max_task_retries).
            old_mailbox = state.mailbox
            state.mailbox = queue.Queue()
            try:
                while True:
                    item = old_mailbox.get_nowait()
                    if item is not None:
                        state.mailbox.put(item)
            except queue.Empty:
                pass
            old_mailbox.put(None)
        self.emit_event("ACTOR_RESTART", actor=state.cls.__name__,
                        attempt=state.restart_count)
        # escalate the restart delay with the restart count (shared policy:
        # jittered exponential from actor_restart_delay_ms)
        delay = BackoffPolicy(
            base_s=_config.get("actor_restart_delay_ms") / 1e3,
            max_s=_config.get("task_retry_max_delay_ms") / 1e3,
            deadline_s=0).delay_for(max(0, state.restart_count - 1))
        timer = threading.Timer(
            delay, lambda: self._util_pool.submit(
                self._place_and_start_actor, state, True))
        timer.daemon = True
        timer.start()

    def get_named_actor(self, name: str, namespace: str = "default"):
        with self.lock:
            actor_id = self.named_actors.get((namespace, name))
            if actor_id is None:
                raise ValueError(f"no actor named {name!r} in namespace "
                                 f"{namespace!r}")
            return self.actors[actor_id]

    # ------------------------------------------------------------ placement

    def create_placement_group(self, bundles: List[ResourceSet], strategy: str,
                               name: str = "") -> PlacementGroupState:
        pg = PlacementGroupState(PlacementGroupID.from_random(), bundles,
                                 strategy, name)
        with self.lock:
            self.placement_groups[pg.pg_id] = pg
        self._util_pool.submit(self._place_pg, pg)
        return pg

    def _place_pg(self, pg: PlacementGroupState):
        deadline = time.monotonic() + _config.get("worker_lease_timeout_s")
        while time.monotonic() < deadline:
            with self.lock:
                states = [self.nodes[nid].state() for nid in self._node_order]
                assignment = schedule_bundles(states, pg.bundles, pg.strategy)
                if assignment is not None:
                    for i, nid in enumerate(assignment):
                        node = self.nodes[nid]
                        node.resources.allocate(pg.bundles[i])
                        node.bundles[(pg.pg_id, i)] = NodeResources(pg.bundles[i])
                    pg.bundle_nodes = assignment
                    pg.state = "CREATED"
                    pg.ready.set()
                    self._kick()
                    return
            time.sleep(0.01)
        pg.state = "INFEASIBLE"
        pg.ready.set()  # wake waiters; they must check pg.state

    def remove_placement_group(self, pg_id: PlacementGroupID):
        with self.lock:
            pg = self.placement_groups.pop(pg_id, None)
            if pg is None or pg.bundle_nodes is None:
                return
            for i, nid in enumerate(pg.bundle_nodes):
                node = self.nodes.get(nid)
                if node is None:
                    continue
                node.bundles.pop((pg_id, i), None)
                if node.alive:
                    node.resources.release(pg.bundles[i])
        self._kick()

    # ------------------------------------------------------------------ misc

    def offload(self, fn: Callable):
        self._util_pool.submit(fn)

    def emit_event(self, kind: str, **fields):
        """Structured event (the RAY_EVENT/EventManager role,
        ``src/ray/util/event.h:42,102``): in-memory ring for the state
        API, JSONL on disk when ``event_log_enabled``."""
        ev = {"ts": time.time(), "kind": kind, **fields}
        self._events.append(ev)  # raylint: allow(data-race) GIL-atomic append to best-effort event ring
        if len(self._events) > 100000:
            del self._events[:50000]  # raylint: allow(data-race) best-effort trim; worst case drops old ring entries
        if _config.get("event_log_enabled"):
            self._persist_event(ev)

    def _persist_event(self, ev: dict):
        import json
        with self._event_file_lock:
            if self._event_file is None:
                d = _config.get("event_log_dir")
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"events_{self.job_id.hex()[:8]}.jsonl")
                self._event_file = open(path, "a", buffering=1)  # raylint: guarded-by(self._event_file_lock)
            try:
                self._event_file.write(json.dumps(ev, default=str) + "\n")
            except Exception as e:
                logger.debug("event log write failed: %s", e)

    def events(self) -> List[dict]:
        return list(self._events)

    def pending_resource_demands(self) -> List[Dict[str, float]]:
        """Resource requests of queued (not yet dispatched) tasks — the
        autoscaler's demand signal (reference: LoadMetrics fed from GCS
        resource reports, ``autoscaler/_private/load_metrics.py``)."""
        with self._pending_cv:
            pending = list(self._pending)
        out = []
        for item in pending:
            spec = item["spec"]
            out.append(self._effective_request(spec).to_dict())
        return out

    def shutdown(self):
        self._shutdown = True
        self._kick()
        with self.lock:
            actor_snapshot = list(self.actors.values())
        for state in actor_snapshot:
            if state.status != ActorState.DEAD:
                self._mark_actor_dead(state, exc.ActorDiedError("shutdown"))
        for node in self.nodes.values():
            node.shutdown()
        self._util_pool.shutdown(wait=False, cancel_futures=True)
        with self._event_file_lock:
            if self._event_file is not None:
                try:
                    self._event_file.close()
                except Exception as e:
                    logger.debug("event log close failed: %s", e)
                self._event_file = None


# -- helpers -----------------------------------------------------------------


def _prof():
    from ray_tpu._private.profiling import get_profiler
    return get_profiler()


def _materialize_env(spec: TaskSpec, actor_state=None):
    """Task-level runtime_env, else the actor's creation-time env."""
    env = spec.options.runtime_env
    if env is None and actor_state is not None:
        env = actor_state.options.runtime_env
    if not env:
        return None
    from ray_tpu._private.runtime_env import get_manager
    return get_manager().get_or_create(env)


def _materialize_env_for_actor(state):
    if not state.options.runtime_env:
        return None
    from ray_tpu._private.runtime_env import get_manager
    return get_manager().get_or_create(state.options.runtime_env)


def _ref_ids_in(args, kwargs) -> List[ObjectID]:
    from ray_tpu.object_ref import ObjectRef
    out = []
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, ObjectRef):
            out.append(a.id())
    return out


def _resolve_refs(obj, runtime: Runtime):
    """Replace top-level ObjectRefs in args with their values (reference
    semantics: refs in args are resolved, nested refs are passed through)."""
    from ray_tpu.object_ref import ObjectRef
    if isinstance(obj, ObjectRef):
        return runtime.get_object(obj.id())
    if isinstance(obj, tuple):
        return tuple(_resolve_refs(a, runtime) if isinstance(a, ObjectRef)
                     else a for a in obj)
    if isinstance(obj, list):
        return [_resolve_refs(a, runtime) if isinstance(a, ObjectRef)
                else a for a in obj]
    if isinstance(obj, dict):
        return {k: (_resolve_refs(v, runtime) if isinstance(v, ObjectRef)
                    else v) for k, v in obj.items()}
    return obj
