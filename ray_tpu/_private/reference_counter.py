"""Distributed-future reference counting with lineage pinning.

Parity with the reference's ``ReferenceCounter``
(``src/ray/core_worker/reference_count.h:61``): tracks local refs and
task-argument pins per object; when counts hit zero the object is freed from
the store, but the *task spec* that produced it is retained by the lineage
table while any downstream object still depends on it, enabling
reconstruction (``object_recovery_manager.h:90``). The runtime here is
host-granular, so "local refs" covers all workers in the owner process;
borrower bookkeeping reduces to refs held by serialized handles.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from ray_tpu._private.ids import ObjectID


class ReferenceCounter:
    def __init__(self, on_zero: Optional[Callable[[ObjectID], None]] = None):
        self._lock = threading.Lock()
        self._local_refs: Dict[ObjectID, int] = {}  # raylint: guarded-by(self._lock)
        self._pins: Dict[ObjectID, int] = {}  # in-flight task arg pins  # raylint: guarded-by(self._lock)
        # Cross-process borrows: oid -> {borrower address -> count}. The
        # owner holds the value while any borrower process retains a
        # deserialized handle (reference_count.h:61 borrower bookkeeping).
        self._borrows: Dict[ObjectID, Dict[str, int]] = {}  # raylint: guarded-by(self._lock)
        self._on_zero = on_zero  # raylint: allow(data-race) set during __init__ before the counter is shared

    def set_on_zero(self, cb: Callable[[ObjectID], None]):
        with self._lock:
            self._on_zero = cb

    def add_local_ref(self, oid: ObjectID):
        with self._lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1

    def _zero_locked(self, oid: ObjectID) -> bool:
        """All three holds — local refs, task pins, remote borrows — gone."""
        return (self._local_refs.get(oid, 0) == 0
                and self._pins.get(oid, 0) == 0
                and not self._borrows.get(oid))

    def remove_local_ref(self, oid: ObjectID):
        cb = None
        with self._lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
            else:
                self._local_refs.pop(oid, None)
                if self._zero_locked(oid):
                    cb = self._on_zero
        if cb is not None:
            cb(oid)

    def pin_for_task(self, oid: ObjectID):
        with self._lock:
            self._pins[oid] = self._pins.get(oid, 0) + 1

    def unpin_for_task(self, oid: ObjectID):
        cb = None
        with self._lock:
            n = self._pins.get(oid, 0) - 1
            if n > 0:
                self._pins[oid] = n
            else:
                self._pins.pop(oid, None)
                if self._zero_locked(oid):
                    cb = self._on_zero
        if cb is not None:
            cb(oid)

    def add_borrow(self, oid: ObjectID, borrower: str):
        """Record that ``borrower`` (a process address) holds the object.
        Idempotent per borrower: the borrower's own reference counter tracks
        how many handles it holds and sends exactly one REMOVE_BORROW when
        its count hits zero, so the owner only needs presence — counting
        each ADD_BORROW would leak when N deserializations pair with one
        removal (reference_count.h:61 tracks borrower worker identity the
        same way)."""
        with self._lock:
            self._borrows.setdefault(oid, {})[borrower] = 1

    def remove_borrow(self, oid: ObjectID, borrower: str):
        cb = None
        with self._lock:
            per = self._borrows.get(oid)
            if per is not None:
                per.pop(borrower, None)
                if not per:
                    self._borrows.pop(oid, None)
            if self._zero_locked(oid):
                cb = self._on_zero
        if cb is not None:
            cb(oid)

    def remove_borrower(self, borrower: str):
        """A borrower process died: drop every borrow it held."""
        zeroed = []
        with self._lock:
            for oid in list(self._borrows):
                per = self._borrows[oid]
                if per.pop(borrower, None) is not None and not per:
                    self._borrows.pop(oid, None)
                    if self._zero_locked(oid):
                        zeroed.append(oid)
        if self._on_zero is not None:
            for oid in zeroed:
                self._on_zero(oid)

    def has_refs(self, oid: ObjectID) -> bool:
        with self._lock:
            return (self._local_refs.get(oid, 0) > 0
                    or self._pins.get(oid, 0) > 0
                    or bool(self._borrows.get(oid)))

    def count(self, oid: ObjectID) -> int:
        with self._lock:
            return (self._local_refs.get(oid, 0) + self._pins.get(oid, 0)
                    + sum(self._borrows.get(oid, {}).values()))

    def live_objects(self) -> Set[ObjectID]:
        with self._lock:
            return set(self._local_refs) | set(self._pins) | set(self._borrows)
