"""Global worker: the public ``init/get/put/wait/kill/cancel`` surface.

Parity with ``python/ray/_private/worker.py`` (``ray.init`` :1003, ``ray.get``
:2162, ``ray.put`` :2276, ``ray.wait`` :2331, ``ray.shutdown`` :1529).
"""

from __future__ import annotations
import logging

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu import exceptions as exc
from ray_tpu._private.config import _config
from ray_tpu._private.ids import JobID, TaskID
from ray_tpu._private.resources import (CPU, TPU, ResourceSet)
from ray_tpu._private.runtime import Runtime, task_context
from ray_tpu.object_ref import ObjectRef

logger = logging.getLogger("ray_tpu")

_global_lock = threading.Lock()
_global = None  # type: Optional["Worker"]
# Subsystems with background threads that outlive the runtime unless torn
# down with it (serve controller loop etc.) register a hook; shutdown()
# drains them first so no stray thread auto-reinitializes the worker
# between an explicit shutdown() and the next init().
_shutdown_hooks: list = []
# sentinel: no init(auth_token=...) has modified the env this session
_UNSET = object()
_displaced_auth_token = _UNSET


def register_shutdown_hook(fn) -> None:
    with _global_lock:
        if fn not in _shutdown_hooks:
            _shutdown_hooks.append(fn)


class Worker:
    def __init__(self, runtime: Runtime, namespace: str):
        self.runtime = runtime
        self.namespace = namespace
        self.driver_task_id = TaskID.for_task(runtime.job_id)


def _detect_num_tpus() -> int:
    """TPU autodetection from the live jax backend — replaces the reference's
    nvidia-smi/GPUtil probing (``resource_spec.py:273-310``)."""
    try:
        import jax
        return len([d for d in jax.devices() if d.platform == "tpu"])
    except Exception:  # raylint: allow(swallow) capability probe: no jax backend
        return 0


def init(num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: Optional[str] = None,
         ignore_reinit_error: bool = False,
         include_dashboard: bool = False,
         dashboard_port: int = 0,
         address: Optional[str] = None,
         auth_token: Optional[str] = None,
         _system_config: Optional[dict] = None,
         _create_default_node: bool = True,
         **kwargs) -> "Worker":
    """Start the runtime (one device-owner process per host).

    ``address="host:port"`` connects this process as a driver to an
    existing cluster's state service (the reference's
    ``ray.init(address=...)`` path, ``worker.py:1003``): tasks and actors
    are then scheduled across the cluster's host daemons. The driver's own
    node contributes no resources unless ``num_cpus``/``num_tpus`` are
    passed explicitly.
    """
    global _global
    with _global_lock:
        if _global is not None:
            if ignore_reinit_error:
                return _global
            raise RuntimeError("ray_tpu.init() called twice; pass "
                               "ignore_reinit_error=True to ignore")
        _config.apply_system_config(_system_config)
        # Always-on flight recorder (process-scoped: it records THIS
        # process, so it survives shutdown()/init() cycles and is sealed
        # by exit hooks or — after a hard kill — by a surviving sweeper).
        from ray_tpu.observability import recorder as _flight
        try:
            _flight.install("driver")
        except Exception as e:
            logger.warning("flight recorder unavailable: %s", e)
        # Perf plane: the driver samples its own stacks too, so /api/profile
        # covers the submitting side of every workload.
        from ray_tpu.observability import perf as _perf
        from ray_tpu.observability import sampler as _stack_sampler
        if _perf.ENABLED:
            _stack_sampler.start()
        if auth_token:
            # Process-wide: every RPC connection (state client, daemon
            # peers) opens with this shared secret (rpc.default_auth_token).
            # Remember what we displaced so shutdown() can restore it —
            # a later init(address=other_cluster) must not inherit this
            # cluster's token.
            global _displaced_auth_token
            _displaced_auth_token = os.environ.get("RAY_TPU_AUTH_TOKEN")  # raylint: guarded-by(_global_lock)
            os.environ["RAY_TPU_AUTH_TOKEN"] = auth_token
        if address is not None:
            from ray_tpu._private.distributed import DistributedRuntime
            amounts: Dict[str, float] = {}
            if num_cpus:
                amounts[CPU] = num_cpus
            if num_tpus:
                amounts[TPU] = num_tpus
            if resources:
                amounts.update(resources)
            runtime = DistributedRuntime(
                state_addr=address, resources=ResourceSet(amounts),
                is_driver=True, namespace=namespace or "default")
            worker = Worker(runtime, namespace or "default")
            if include_dashboard:
                from ray_tpu.dashboard import start_dashboard
                try:
                    head = start_dashboard(address, port=dashboard_port)
                except BaseException:
                    # a failed dashboard must not leave a live runtime
                    # behind a half-initialized worker (retrying init()
                    # would then raise "called twice")
                    runtime.shutdown()
                    raise
                worker.dashboard_head = head
                worker.dashboard_port = head.port
            _global = worker  # raylint: allow(data-race) installed under _global_lock; unlocked peeks like is_initialized are GIL-atomic snapshots
            return _global
        runtime = Runtime()
        if _create_default_node:
            amounts: Dict[str, float] = {
                CPU: num_cpus if num_cpus is not None else float(os.cpu_count() or 1),
            }
            detected_tpus = _detect_num_tpus()
            n_tpus = num_tpus if num_tpus is not None else detected_tpus
            if n_tpus:
                amounts[TPU] = n_tpus
            if resources:
                amounts.update(resources)
            runtime.add_node(ResourceSet(amounts))
        _global = Worker(runtime, namespace or "default")  # raylint: allow(data-race) installed under _global_lock; unlocked peeks like is_initialized are GIL-atomic snapshots
        if include_dashboard:
            from ray_tpu._private.state_server import start_state_server
            # raylint: allow(data-race) dashboard_port set under _global_lock during init
            _global.dashboard_port = start_state_server(dashboard_port)
        return _global


def shutdown():
    global _global
    from ray_tpu.observability import sampler as _stack_sampler
    _stack_sampler.stop()
    with _global_lock:
        hooks, _shutdown_hooks[:] = list(_shutdown_hooks), []
    for hook in hooks:
        try:
            hook()
        except Exception as e:
            logger.warning("shutdown hook failed: %s", e)
    with _global_lock:
        if _global is not None:
            head = getattr(_global, "dashboard_head", None)
            if head is not None:
                try:
                    head.stop()
                except Exception as e:
                    logger.debug("dashboard head stop failed: %s", e)
            elif getattr(_global, "dashboard_port", None) is not None:
                from ray_tpu._private.state_server import stop_state_server
                stop_state_server()
            _global.runtime.shutdown()
            _global = None  # raylint: allow(data-race) cleared under _global_lock at shutdown; unlocked peeks are GIL-atomic snapshots
        global _displaced_auth_token
        if _displaced_auth_token is not _UNSET:
            if _displaced_auth_token is None:
                os.environ.pop("RAY_TPU_AUTH_TOKEN", None)
            else:
                os.environ["RAY_TPU_AUTH_TOKEN"] = _displaced_auth_token
            _displaced_auth_token = _UNSET


def is_initialized() -> bool:
    return _global is not None


def global_worker() -> Worker:
    if _global is None:
        init()
    return _global  # type: ignore[return-value]


def try_global_runtime() -> Optional[Runtime]:
    return _global.runtime if _global is not None else None


def current_task_id() -> TaskID:
    tid = task_context.task_id
    if tid is not None:
        return tid
    return global_worker().driver_task_id


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    w = global_worker()
    oid = w.runtime.put_object(value)
    return ObjectRef(oid, owner=w.runtime)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    w = global_worker()
    if isinstance(refs, ObjectRef):
        return w.runtime.get_object(refs.id(), timeout=timeout)
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects ObjectRef or list, got {type(refs)}")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list items must be ObjectRef, got {type(r)}")
    # Batch resolution: one shared deadline; distributed runtimes overlap
    # the refs that need the wire (remote fetches, in-flight pushed tasks)
    # instead of paying one serialized round trip per ref.
    return w.runtime.get_objects([r.id() for r in refs], timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None,
         fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    """Parity with ``ray.wait`` (worker.py:2331): returns (ready, not_ready)
    preserving input order, blocking until ``num_returns`` ready or timeout."""
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    w = global_worker()
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        ready = [r for r in refs if w.runtime.object_ready(r.id())]
        if len(ready) >= num_returns or (
                deadline is not None and time.monotonic() >= deadline):
            # Return at most num_returns ready refs (ray.wait contract).
            ready_set = set(ready[:num_returns])
            ready_list = [r for r in refs if r in ready_set]
            not_ready = [r for r in refs if r not in ready_set]
            return ready_list, not_ready
        # Wake as soon as any still-pending ref seals locally (checked
        # under the seal condvar so nothing is lost); the 10ms cap covers
        # completions that seal in another process.
        pending = [r.id() for r in refs if r not in set(ready)]
        w.runtime._wait_for_seal(
            lambda: any(w.runtime._sealed_locally(o) for o in pending), 0.01)


def kill(actor, *, no_restart: bool = True):
    from ray_tpu.actor import ActorHandle
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle; use cancel() for tasks")
    global_worker().runtime.kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    if not isinstance(ref, ObjectRef):
        raise TypeError("cancel() expects an ObjectRef")
    global_worker().runtime.cancel_task(ref.task_id(), force=force)


def get_actor(name: str, namespace: Optional[str] = None):
    from ray_tpu.actor import ActorHandle
    w = global_worker()
    state = w.runtime.get_named_actor(name, namespace or w.namespace)
    return ActorHandle._from_state(state)


def available_resources() -> Dict[str, float]:
    w = global_worker()
    total: Dict[str, float] = {}
    for ns in w.runtime.node_states():
        if not ns.alive:
            continue
        for k, v in ns.resources.available.to_dict().items():
            total[k] = total.get(k, 0.0) + v
    return total


def cluster_resources() -> Dict[str, float]:
    w = global_worker()
    total: Dict[str, float] = {}
    for ns in w.runtime.node_states():
        if not ns.alive:
            continue
        for k, v in ns.resources.total.to_dict().items():
            total[k] = total.get(k, 0.0) + v
    return total


def nodes() -> List[dict]:
    w = global_worker()
    return [{
        "NodeID": ns.node_id.hex(),
        "Alive": ns.alive,
        "State": ("DRAINING" if getattr(ns, "draining", False)
                  else "ALIVE" if ns.alive else "DEAD"),
        "Resources": ns.resources.total.to_dict(),
        "Available": ns.resources.available.to_dict(),
    } for ns in w.runtime.node_states()]


def drain_node(node_id: str, reason: str = "",
               deadline_s: float = 0.0) -> None:
    """Gracefully drain a cluster node: flip it to DRAINING at the state
    service so the scheduler stops placing work there, then let the
    node's own drain orchestrator migrate its workload (in-flight tasks
    finish, actors checkpoint and restart elsewhere, sole-copy objects
    re-replicate) before it decommissions.

    ``node_id`` is the hex id reported by :func:`nodes`. ``deadline_s``
    is the migration budget; 0 uses the ``drain_deadline_s`` config.
    """
    w = global_worker()
    state = getattr(w.runtime, "state", None)
    if state is None:
        raise RuntimeError(
            "drain_node requires a distributed runtime "
            "(ray_tpu.init(address=...)); the in-process runtime has no "
            "node lifecycle")
    state.drain_node(bytes.fromhex(node_id), reason, deadline_s)


def timeline(filename: Optional[str] = None):
    """Chrome-tracing dump of task/actor spans (reference: ``ray timeline``
    CLI ``scripts.py:1755`` → ``GlobalState.chrome_tracing_dump``
    ``state.py:419``). On a cluster, spans from EVERY daemon process are
    merged (cross-process trace propagation)."""
    rt = try_global_runtime()
    cluster_fetch = getattr(rt, "cluster_timeline", None)
    if cluster_fetch is not None:
        import json as _json
        trace = cluster_fetch()
        if filename is None:
            return trace
        from ray_tpu.checkpoint.manifest import atomic_write_bytes
        atomic_write_bytes(filename, _json.dumps(trace).encode())
        return filename
    from ray_tpu._private.profiling import dump_timeline
    return dump_timeline(filename)


def set_profiling_enabled(enabled: bool) -> None:
    """Switch span recording on/off — cluster-wide when connected (the
    daemons' buffers feed ``timeline()``)."""
    rt = try_global_runtime()
    cluster_set = getattr(rt, "set_cluster_profiling", None)
    if cluster_set is not None:
        cluster_set(enabled)
        return
    _config.set("profiling_enabled", bool(enabled))


def set_tracing_enabled(enabled: bool) -> None:
    """Switch end-to-end trace-context propagation on/off — cluster-wide
    when connected (daemons adopt it via the timeline control RPC)."""
    from ray_tpu import observability
    rt = try_global_runtime()
    cluster_set = getattr(rt, "set_cluster_tracing", None)
    if cluster_set is not None:
        cluster_set(enabled)
        return
    if enabled:
        observability.enable()
    else:
        observability.disable()


def register_named_function(name: str, fn=None):
    """Publish a function for cross-language callers (the C++ worker API
    submits by name with JSON args). Usable as a decorator::

        @ray_tpu.register_named_function("add")
        def add(a, b): return a + b
    """
    if fn is None:
        def deco(f):
            register_named_function(name, f)
            return f
        return deco
    runtime = global_worker().runtime
    reg = getattr(runtime, "register_named_function", None)
    if reg is None:
        raise RuntimeError("named functions need a cluster runtime "
                           "(init(address=...) or a daemon)")
    reg(name, fn)
    return fn


def register_named_actor_class(name: str, cls=None):
    """Publish an actor class for cross-language callers — the typed C++
    ``Actor("name").Remote(args...)`` surface (reference
    ``cpp/include/ray/api/actor_creator.h:1`` role, shaped for this
    runtime's contract: Python defines the class, any language drives
    it). Usable as a decorator::

        @ray_tpu.register_named_actor_class("Counter")
        class Counter: ...

    Under the hood three named functions carry the actor protocol over
    JSON: ``__actor_new__::<name>`` creates a NAMED actor from the
    registered class (the daemon executing the creation owns it; the
    name makes it reachable from every process), and the generic
    ``__actor_call__`` / ``__actor_kill__`` route method calls and
    termination through ``get_actor`` — the ordinary, fully-tested
    Python actor path."""
    if cls is None:
        def deco(c):
            register_named_actor_class(name, c)
            return c
        return deco

    import ray_tpu

    def _new(actor_name, *args):
        remote_cls = ray_tpu.remote(cls)
        remote_cls.options(name=actor_name).remote(*args)
        return actor_name

    def _call(actor_name, method, *args):
        h = ray_tpu.get_actor(actor_name)
        return ray_tpu.get(getattr(h, method).remote(*args))

    def _kill(actor_name):
        ray_tpu.kill(ray_tpu.get_actor(actor_name))
        return True

    register_named_function(f"__actor_new__::{name}", _new)
    register_named_function("__actor_call__", _call)
    register_named_function("__actor_kill__", _kill)
    return cls
