"""Per-host runtime daemon: ``python -m ray_tpu._private.host_daemon``.

The raylet-equivalent process (``src/ray/raylet/main.cc:309``), except the
worker pool is threads inside this same process because a TPU host's
devices are owned by exactly one process (libtpu single-owner): this daemon
IS the device owner, the executor, and the per-host object store in one.
It registers with the state service, heartbeats, admits pushed tasks, and
serves object fetches until drained or its state-service connection is
irrecoverably lost.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import time


def _install_thread_profiler(out_dir: str):
    """RAY_TPU_PROFILE_DIR=<dir>: cProfile EVERY thread of this daemon and
    dump one .pstats per thread at exit (merge with pstats.Stats.add).
    The hot paths run on the RPC pool and dispatcher threads, which
    ordinary main-thread cProfile never sees."""
    import atexit
    import cProfile
    import threading

    os.makedirs(out_dir, exist_ok=True)
    profiles = []
    lock = threading.Lock()
    orig_run = threading.Thread.run

    def run(self):
        prof = cProfile.Profile()
        with lock:
            profiles.append((self.name, prof))
        try:
            prof.runcall(orig_run, self)
        finally:
            pass

    threading.Thread.run = run
    main_prof = cProfile.Profile()
    main_prof.enable()
    profiles.append(("main", main_prof))

    def dump():
        main_prof.disable()
        for i, (name, prof) in enumerate(list(profiles)):
            safe = "".join(c if c.isalnum() else "_" for c in name)[:60]
            try:
                prof.dump_stats(os.path.join(
                    out_dir, f"daemon{os.getpid()}_{i}_{safe}.pstats"))
            except Exception:  # noqa: BLE001  # raylint: allow(swallow) best-effort profile dump at exit
                pass

    atexit.register(dump)


class _ProbeState:
    """Failure bookkeeping for the ``preempt_probe_url`` poll.

    A flapping or unreachable metadata endpoint must not be re-probed at
    the full ``preempt_poll_ms`` cadence (1-second connect timeouts at a
    500 ms poll period pile up), so consecutive failures pace the next
    attempt with the shared :class:`BackoffPolicy` (``preempt_poll_ms``
    base, ``backoff_max_ms`` cap, no jitter — deterministic pacing).
    The consecutive-failure count is exported as the
    ``preempt_probe_failures`` gauge and published into the state KV
    (``preempt`` namespace) so the doctor can flag a blind watcher and
    the hazard estimator can treat the node as riskier.
    """

    def __init__(self, runtime=None):
        from ray_tpu._private.backoff import BackoffPolicy
        from ray_tpu._private.config import _config
        from ray_tpu.util import metrics as _metrics
        poll_s = max(0.1, _config.get("preempt_poll_ms") / 1e3)
        self._policy = BackoffPolicy(base_s=poll_s, jitter=False,
                                     label="preempt-probe")
        self._runtime = runtime
        self._not_before = 0.0
        self.failures = 0
        self._gauge = _metrics.Gauge(
            "preempt_probe_failures",
            "consecutive preempt_probe_url failures on this node (a "
            "blind preemption watcher; the doctor flags it past "
            "preempt_probe_failure_threshold)")
        self._gauge.set(0)

    def throttled(self, now: float) -> bool:
        return now < self._not_before

    def success(self, now: float) -> None:
        if self.failures:
            self.failures = 0
            self._gauge.set(0)
            self._publish()
        self._not_before = 0.0

    def failure(self, now: float) -> None:
        self.failures += 1
        self._gauge.set(self.failures)
        self._not_before = now + self._policy.delay_for(self.failures - 1)
        self._publish()

    def _publish(self) -> None:
        state = getattr(self._runtime, "state", None)
        if state is None:
            return
        try:
            from ray_tpu.autoscaler import hazard as _hazard
            _hazard.publish_probe_health(
                state, self._runtime.local_node.node_id.hex(),
                self.failures)
        except Exception as e:  # noqa: BLE001
            logging.debug("probe health publish failed: %s", e)


def _preempt_signaled(node_tag: str,
                      probe: "Optional[_ProbeState]" = None) -> "str | None":
    """One poll of the pluggable preemption watcher. Two sources, checked
    in order:

    - the ``node.preempt`` chaos point — the deterministic test vehicle
      (a "drop" return IS the eviction notice; side-effect-free, so the
      signal composes with any other chaos running); and
    - ``preempt_probe_url`` — a GCE-metadata-style HTTP probe for real
      TPU VMs (``.../instance/preempted`` returns TRUE once the eviction
      is scheduled; anything but NONE/FALSE counts as a notice). When a
      ``probe`` state is supplied, failed probes back off instead of
      retrying at every poll, and consecutive failures are exported.

    Returns the drain reason, or None when no preemption is pending.
    """
    from ray_tpu import chaos
    if chaos.ENABLED and chaos.inject("node.preempt",
                                      node=node_tag) == "drop":
        return "preemption notice (chaos)"
    from ray_tpu._private.config import _config
    url = _config.get("preempt_probe_url")
    if url:
        now = time.monotonic()
        if probe is not None and probe.throttled(now):
            return None
        try:
            import urllib.request
            req = urllib.request.Request(
                url, headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=1.0) as resp:
                body = resp.read(256).decode(
                    "utf-8", "replace").strip().upper()
        except Exception:  # noqa: BLE001  # raylint: allow(swallow) probe outage must not kill the watcher; the backoff-paced next poll retries
            if probe is not None:
                probe.failure(time.monotonic())
            return None
        if probe is not None:
            probe.success(now)
        if body not in ("", "NONE", "FALSE"):
            return f"preemption notice (probe: {body[:40]})"
    return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="ray_tpu host daemon")
    parser.add_argument("--state-addr", required=True,
                        help="host:port of the state service")
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--resources", type=str, default="{}",
                        help="JSON dict of custom resources")
    parser.add_argument("--labels", type=str, default="{}")
    parser.add_argument("--listen-host", type=str, default="127.0.0.1")
    parser.add_argument("--heartbeat-interval-s", type=float, default=1.0)
    parser.add_argument("--ready-file", type=str, default="",
                        help="write our RPC address here once serving")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[daemon %(asctime)s] %(levelname)s %(message)s")
    # Recent-log ring served over NODE_DEBUG (dashboard log viewer).
    from ray_tpu._private import log_ring
    log_ring.install()

    # Flight recorder: installed before the runtime so even a crash during
    # startup leaves a recording; sealed by exit hooks, or posthumously by
    # a surviving daemon/doctor if this process is SIGKILL'd.
    from ray_tpu.observability import recorder as _flight
    recorder = None
    try:
        recorder = _flight.install("host_daemon")
    except Exception:
        logging.warning("flight recorder unavailable", exc_info=True)

    prof_dir = os.environ.get("RAY_TPU_PROFILE_DIR")
    if prof_dir:
        _install_thread_profiler(prof_dir)

    # Honor JAX_PLATFORMS even when a site hook already imported jax and a
    # device plugin claimed the default platform (the env var alone is read
    # too early to win) — a CPU test daemon must never initialize the TPU.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            logging.warning("could not pin jax platform to %r", plat,
                            exc_info=True)

    from ray_tpu._private import worker as _worker
    from ray_tpu._private.distributed import DistributedRuntime
    from ray_tpu._private.resources import CPU, TPU, ResourceSet
    from ray_tpu._private.worker import _detect_num_tpus

    amounts = {CPU: args.num_cpus if args.num_cpus is not None
               else float(os.cpu_count() or 1)}
    n_tpus = (args.num_tpus if args.num_tpus is not None
              else _detect_num_tpus())
    if n_tpus:
        amounts[TPU] = n_tpus
    amounts.update(json.loads(args.resources))

    runtime = DistributedRuntime(
        state_addr=args.state_addr, resources=ResourceSet(amounts),
        is_driver=False, listen_host=args.listen_host,
        labels=json.loads(args.labels),
        heartbeat_interval_s=args.heartbeat_interval_s)

    # Install as the process-global worker so tasks executing here can call
    # ray_tpu.get/put/remote/etc. (the driver-API-inside-worker contract).
    with _worker._global_lock:
        _worker._global = _worker.Worker(runtime, "default")  # raylint: allow(data-race) installed once at daemon bootstrap under _global_lock; is_initialized's unlocked peek is a GIL-atomic snapshot

    stop = {"flag": False}

    def _on_signal(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(runtime.address + "\n")
        os.replace(tmp, args.ready_file)
    logging.info("host daemon %s serving at %s (resources %s)",
                 runtime.local_node.node_id.hex()[:8], runtime.address,
                 amounts)
    if recorder is not None:
        recorder.set_label(f"node:{runtime.local_node.node_id.hex()[:8]}")

    # Per-node reporter agent (dashboard/agent.py role): publishes proc +
    # store stats into the state-service KV for the dashboard head.
    reporter = None
    try:
        from ray_tpu.dashboard.agent import NodeReporterAgent
        reporter = NodeReporterAgent(runtime)
        reporter.start()
    except Exception:
        logging.warning("node reporter unavailable", exc_info=True)

    # Perf plane: per-process stack sampler (profiles federate through
    # NODE_DEBUG include_stacks -> dashboard /api/profile).
    from ray_tpu.observability import perf as _perf
    from ray_tpu.observability import sampler as _stack_sampler
    if _perf.ENABLED:
        _stack_sampler.start()

    # Posthumous-sealing sweep: a surviving daemon on the host seals crash
    # bundles for siblings that died without running their hooks (SIGKILL).
    from ray_tpu._private.config import _config
    node_tag = runtime.local_node.node_id.hex()[:8]
    preempt_poll_s = max(0.1, _config.get("preempt_poll_ms") / 1e3)
    probe_state = _ProbeState(runtime)
    next_sweep = time.monotonic() + 2.0
    next_preempt_probe = time.monotonic() + preempt_poll_s
    try:
        while not stop["flag"] and not runtime._hb_stop.is_set():
            # raylint: allow(bare-retry) serve-loop pacing, not a retry: the swallowed sweep is periodic best-effort work
            time.sleep(0.2)
            # Preemption watcher: an eviction notice starts the graceful
            # drain (workload migration) instead of waiting to be killed.
            if (not runtime.draining
                    and time.monotonic() >= next_preempt_probe):
                next_preempt_probe = time.monotonic() + preempt_poll_s
                reason = _preempt_signaled(node_tag, probe=probe_state)
                if reason:
                    logging.warning("preemption notice: draining node %s "
                                    "(%s)", node_tag, reason)
                    runtime.begin_drain(
                        reason,
                        deadline_s=_config.get("preempt_lead_s"))
            if recorder is not None and time.monotonic() >= next_sweep:
                next_sweep = time.monotonic() + 2.0
                try:
                    _flight.seal_orphans(sealed_by="host_daemon")
                except Exception:  # noqa: BLE001  # raylint: allow(swallow) sweep is best-effort; next pass retries
                    pass
    finally:
        _stack_sampler.stop()
        if reporter is not None:
            reporter.stop()
        try:
            runtime.shutdown()
        except Exception:
            logging.exception("daemon shutdown error")
        if recorder is not None:
            try:
                recorder.close(clean=True)
            except Exception:  # noqa: BLE001  # raylint: allow(swallow) exiting anyway; recording stays unsealed at worst
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
