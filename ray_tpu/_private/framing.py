"""Framed out-of-band serialization (pickle protocol 5).

The reference gets zero-copy numpy out of plasma by pinning arrays in shm
(serialization.py + plasma). Same idea here: large array payloads are
pickled with out-of-band buffers and laid out in a frame —

  MAGIC  u32 idx_len  idx(header_len, nbuf, buf_lens...)  header
  [64-aligned buffer 0] [64-aligned buffer 1] ...

— so the ENCODE side copies each array at most once and the DECODE side
copies nothing: arrays are reconstructed backed by views into the received
frame (a TCP blob, pinned shared-arena pages, or the local store's arena).

Two encoders share the layout:

- ``dumps_framed``: materializes the whole frame into one bytearray (one
  copy per array). Used where a contiguous payload is required.
- ``FramedPayload``: keeps the array bytes IN their source buffers and
  exposes the frame as a gather list, so a fetch/push chunk leaves via
  ``sendmsg`` scatter-gather with zero serialize-side copies.

This module is the single owner of the layout; ``distributed.py`` (wire)
and ``object_store.py`` (arena receive slots) both decode through it.
"""

from __future__ import annotations

import pickle
import struct as _struct
from typing import Any, List, Tuple

import cloudpickle

FRAME_MAGIC = b"RTF5"
_PAD = bytes(64)  # alignment gaps are always < 64 bytes


def frame_layout(header_len: int, buf_lens: List[int], trace: bytes = b""):
    """Frame geometry. ``trace`` is an optional provenance blob appended
    to the index (after the fixed ``header_len, nbuf, buf_lens`` part) —
    decoders detect it as ``idx_len > 8 + 8 * nbuf``. An empty trace keeps
    the frame byte-identical to the pre-trace format, which the checkpoint
    engine's content-addressed dedup relies on."""
    idx = _struct.pack(f">II{len(buf_lens)}Q", header_len, len(buf_lens),
                       *buf_lens) + trace
    header_off = 4 + 4 + len(idx)
    off = (header_off + header_len + 63) & ~63
    buf_offs = []
    for ln in buf_lens:
        buf_offs.append(off)
        off = (off + ln + 63) & ~63
    return off, header_off, buf_offs, idx


def _pickle_oob(value: Any):
    """-> (header_bytes, [byte-cast readonly buffer views])."""
    pbufs: List[Any] = []
    header = cloudpickle.dumps(value, protocol=5,
                               buffer_callback=pbufs.append)
    raws = []
    for b in pbufs:
        try:
            raws.append(b.raw())
        except Exception:  # raylint: allow(swallow) raw() raises for non-contiguous buffers by contract; materialize instead
            raws.append(memoryview(bytes(b)))
    return header, raws


def dumps_framed(value: Any, trace: bytes = b"") -> bytearray:
    """Serialize into one framed payload (single copy per array)."""
    header, raws = _pickle_oob(value)
    total, hoff, boffs, idx = frame_layout(len(header),
                                           [r.nbytes for r in raws], trace)
    out = bytearray(total)
    out[0:4] = FRAME_MAGIC
    out[4:8] = _struct.pack(">I", len(idx))
    out[8:8 + len(idx)] = idx
    out[hoff:hoff + len(header)] = header
    for off, r in zip(boffs, raws):
        out[off:off + r.nbytes] = r
    # returned as the bytearray itself — bytes(out) would duplicate the
    # whole frame; consumers slice per-chunk
    return out


def loads_framed(view) -> Tuple[Any, bool]:
    """Decode a frame from ``view`` (bytes or memoryview).

    Returns ``(value, zero_copy)``: when ``zero_copy`` the value's arrays
    reference ``view`` directly — the caller must keep the backing alive
    (and pinned, for arena pages) for the value's lifetime."""
    mv = memoryview(view).toreadonly()  # sealed objects are immutable —
    # a writable view into shared arena pages must never leak to users
    if mv[:4] != FRAME_MAGIC:
        return pickle.loads(mv), False  # legacy plain-pickle payload
    (idx_len,) = _struct.unpack(">I", mv[4:8])
    header_len, nbuf = _struct.unpack_from(">II", mv, 8)
    buf_lens = list(_struct.unpack_from(f">{nbuf}Q", mv, 16))
    # Offsets from idx_len directly, so frames with a trailing trace blob
    # in the index (idx_len > 8 + 8*nbuf) decode identically.
    hoff = 8 + idx_len
    off = (hoff + header_len + 63) & ~63
    boffs = []
    for ln in buf_lens:
        boffs.append(off)
        off = (off + ln + 63) & ~63
    header = bytes(mv[hoff:hoff + header_len])
    buffers = [mv[off:off + ln] for off, ln in zip(boffs, buf_lens)]
    return pickle.loads(header, buffers=buffers), nbuf > 0


def frame_trace(view) -> str:
    """The provenance blob embedded in a frame's index, decoded to str
    (``"trace_id:span_id"``), or ``""`` when absent / not an RTF5 frame.
    Reads only the fixed-size prefix — never decodes the payload."""
    mv = memoryview(view)
    if len(mv) < 16 or bytes(mv[:4]) != FRAME_MAGIC:
        return ""
    (idx_len,) = _struct.unpack(">I", mv[4:8])
    (nbuf,) = _struct.unpack_from(">I", mv, 12)
    base = 8 + 8 * nbuf
    if idx_len <= base:
        return ""
    try:
        return bytes(mv[8 + base:8 + idx_len]).decode("ascii")
    except UnicodeDecodeError:
        return ""


class FramedPayload:
    """A framed serialization whose array bytes never left their source
    buffers. Byte-identical on the wire to ``dumps_framed(value)``, but
    exposed as (offset, view) segments: ``slices(a, b)`` returns the
    gather list for any byte range, ready for ``sendmsg`` scatter-gather.

    Holding a ``FramedPayload`` keeps the source arrays alive (the views
    reference their exporters), which is exactly the serve-cache contract:
    a chunked fetch must see stable bytes even if the object is freed
    from the store mid-transfer.
    """

    __slots__ = ("_segments", "_total", "trace")

    def __init__(self, value: Any, trace: bytes = b""):
        header, raws = _pickle_oob(value)
        total, hoff, boffs, idx = frame_layout(len(header),
                                               [r.nbytes for r in raws],
                                               trace)
        self.trace = trace
        prefix = bytearray(hoff + len(header))
        prefix[0:4] = FRAME_MAGIC
        prefix[4:8] = _struct.pack(">I", len(idx))
        prefix[8:8 + len(idx)] = idx
        prefix[hoff:] = header
        segments = [(0, memoryview(prefix).toreadonly())]
        for off, r in zip(boffs, raws):
            segments.append((off, r.toreadonly()))
        self._segments = segments
        self._total = total

    def __len__(self) -> int:
        return self._total

    @property
    def pieces(self) -> List[memoryview]:
        """The whole frame as a contiguous-coverage gather list."""
        return self.slices(0, self._total)

    def slices(self, start: int, end: int) -> List[memoryview]:
        """Gather list covering exactly ``[start, min(end, len))`` of the
        frame; alignment padding appears as zero-filled pieces."""
        end = min(end, self._total)
        out: List[memoryview] = []
        pos = start
        for off, mv in self._segments:
            if pos >= end:
                break
            gap_end = min(off, end)
            while pos < gap_end:  # zeros between segments (< 64 bytes)
                take = min(len(_PAD), gap_end - pos)
                out.append(memoryview(_PAD)[:take])
                pos += take
            seg_end = off + len(mv)
            if pos < seg_end and pos < end:
                lo, hi = pos - off, min(seg_end, end) - off
                out.append(mv[lo:hi])
                pos = off + hi
        while pos < end:  # trailing pad up to the 64-aligned total
            take = min(len(_PAD), end - pos)
            out.append(memoryview(_PAD)[:take])
            pos += take
        return out

    def write_into(self, dest: memoryview) -> None:
        """Materialize the frame into ``dest`` (arena slot landing)."""
        pos = 0
        for p in self.pieces:
            n = len(p)
            dest[pos:pos + n] = p
            pos += n
