"""Per-task / per-actor runtime environments.

Parity with ``python/ray/_private/runtime_env/`` (working_dir/py_modules
packaging ``packaging.py``, env_vars, URI-keyed caching ``uri_cache.py``;
materialized by the per-node runtime-env agent
``dashboard/modules/runtime_env/runtime_env_agent.py:159,256``).

Host-granular redesign: workers are threads of the device-owner process,
so "materialize" means (a) stage working_dir/py_modules into a
content-hashed cache directory and put them on ``sys.path``, and (b)
apply ``env_vars`` around execution under the environment GATE:
``os.environ``/``sys.path`` are process-wide, so only one *distinct*
environment can be active at a time — but any number of tasks sharing
that same environment run concurrently (refcounted entry/exit; the first
applier mutates, the last restorer undoes). This replaces the earlier
whole-body global lock, which serialized even identical-env tasks.
``pip``/``conda`` fields are rejected: the runtime has no network egress
and one shared interpreter.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import shutil
import sys
import threading
import zipfile
from typing import Any, Dict, List, Optional

_CACHE_DIR = "/tmp/ray_tpu/runtime_envs"
_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip"}


class _EnvGate:
    """Admission gate over the process-wide environment: tasks with the
    SAME env run concurrently (refcount); a different env waits until the
    count drains, then swaps. The first entrant applies the mutations and
    snapshots what it displaced; the last leaver restores."""

    def __init__(self):
        self.cv = threading.Condition()
        self.active_key: Optional[str] = None
        self.count = 0
        # While a nested DIFFERENT env has mutated the process env, new
        # same-outer-env entrants must be held out too — otherwise they
        # run with the nested env's env_vars visible (the silent bleed
        # the exclusivity wait exists to prevent).
        self.nested_active = 0
        self._saved: Dict[str, Optional[str]] = {}
        self._inserted: List[str] = []
        self._depth = threading.local()  # nested applied() on one thread

    def enter(self, env: "MaterializedEnv"):
        depth = getattr(self._depth, "n", 0)
        self._depth.n = depth + 1
        if depth > 0:
            # Nested applied() on one thread. A nested env with the SAME
            # content is a no-op re-entry. A DIFFERENT env would mutate
            # the process environment underneath concurrently running
            # same-env peers (count > 1) — that silent bleed is worse
            # than refusing, so it requires exclusivity.
            with self.cv:
                if env.key == self.active_key:
                    self._push_nested(({}, [], False))
                    return
                if not self.cv.wait_for(lambda: self.count <= 1,
                                        timeout=5.0):
                    self._depth.n -= 1
                    raise RuntimeEnvError(
                        "nested runtime_env with a different environment "
                        "while sibling tasks share the outer environment: "
                        "unsupported in the shared-interpreter runtime "
                        "(the reference isolates via per-worker "
                        "processes)")
                saved = {k: os.environ.get(k) for k in env.env_vars}
                inserted = []
                os.environ.update(env.env_vars)
                for p in env.sys_paths:
                    if p not in sys.path:
                        sys.path.insert(0, p)
                        inserted.append(p)
                self.nested_active += 1
                self._push_nested((saved, inserted, True))
            return
        with self.cv:
            while (self.active_key not in (None, env.key)
                   or self.nested_active > 0):
                self.cv.wait(timeout=1.0)
            if self.active_key is None:
                self.active_key = env.key
                self._apply(env, save=True)
            self.count += 1

    def _push_nested(self, snapshot):
        stack = getattr(self._depth, "stack", None)
        if stack is None:
            stack = self._depth.stack = []
        stack.append(snapshot)

    def exit(self, env: "MaterializedEnv"):
        self._depth.n = getattr(self._depth, "n", 1) - 1
        if self._depth.n > 0:
            saved, inserted, mutated = self._depth.stack.pop()
            with self.cv:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                for p in inserted:
                    with contextlib.suppress(ValueError):
                        sys.path.remove(p)
                if mutated:
                    self.nested_active -= 1
                    self.cv.notify_all()
            return
        with self.cv:
            self.count -= 1
            if self.count == 0:
                self._restore()
                self.active_key = None
            # notify on EVERY decrement: nested-env entrants wait for
            # count <= 1, not just 0
            self.cv.notify_all()

    def _apply(self, env: "MaterializedEnv", save: bool):
        if save:
            self._saved = {k: os.environ.get(k) for k in env.env_vars}
            self._inserted = []
        os.environ.update(env.env_vars)
        for p in env.sys_paths:
            if p not in sys.path:
                sys.path.insert(0, p)
                if save:
                    self._inserted.append(p)

    def _restore(self):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for p in self._inserted:
            with contextlib.suppress(ValueError):
                sys.path.remove(p)
        self._saved, self._inserted = {}, []


_ENV_GATE = _EnvGate()


class RuntimeEnvError(ValueError):
    pass


def validate(runtime_env: Dict[str, Any]) -> None:
    unsupported = set(runtime_env) - _SUPPORTED
    if unsupported & {"conda", "container"}:
        raise RuntimeEnvError(
            f"runtime_env fields {sorted(unsupported)} are not supported: "
            "the host-granular runtime shares one interpreter per host "
            "(no interpreter/image swap). Use 'pip' for per-task package "
            "prefixes, or bake dependencies into the image.")
    if unsupported:
        raise RuntimeEnvError(
            f"unknown runtime_env fields {sorted(unsupported)}; "
            f"supported: {sorted(_SUPPORTED)}")


def _hash_path(path: str) -> str:
    """Content hash of a file or directory tree (the URI in uri_cache)."""
    h = hashlib.blake2b(digest_size=16)
    if os.path.isfile(path):
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    else:
        for root, dirs, files in sorted(os.walk(path)):
            dirs.sort()
            for name in sorted(files):
                p = os.path.join(root, name)
                h.update(os.path.relpath(p, path).encode())
                with open(p, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
    return h.hexdigest()


def _stage(path: str) -> str:
    """Copy/extract ``path`` (dir or .zip) into the content-hash cache and
    return the staged directory (idempotent — cache hit is free)."""
    if not os.path.exists(path):
        raise RuntimeEnvError(f"runtime_env path {path!r} does not exist")
    digest = _hash_path(path)
    target = os.path.join(_CACHE_DIR, digest)
    if os.path.isdir(target):
        return target
    tmp = target + ".staging"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            z.extractall(tmp)
    elif os.path.isdir(path):
        shutil.copytree(path, tmp, dirs_exist_ok=True)
    else:
        raise RuntimeEnvError(
            f"working_dir/py_modules must be a directory or zip: {path!r}")
    try:
        os.replace(tmp, target)
    except OSError:
        # A concurrent materialization won the race; use its copy.
        shutil.rmtree(tmp, ignore_errors=True)
    return target


_PIP_BUILD_LOCKS: Dict[str, threading.Lock] = {}
_PIP_BUILD_LOCKS_GUARD = threading.Lock()


def _pip_build_lock(target: str) -> threading.Lock:
    """Per-TARGET build lock: same-env racers serialize (one pip run),
    while builds of unrelated envs — each potentially minutes long —
    proceed in parallel."""
    with _PIP_BUILD_LOCKS_GUARD:
        return _PIP_BUILD_LOCKS.setdefault(target, threading.Lock())


def _materialize_pip(spec, counter: Optional[list] = None) -> str:
    """Build (or reuse) a pip package prefix for a runtime env.

    Reference parity: ``python/ray/_private/runtime_env/pip.py:1`` +
    ``uri_cache.py:1`` — but redesigned for the thread-worker runtime:
    the reference builds a virtualenv because it launches worker
    PROCESSES inside it; here workers are threads of the device-owner
    daemon, so "materialize" means ``pip install --target`` into a
    requirements-keyed cache directory that the environment gate puts on
    ``sys.path`` for the task's duration. Same interpreter, so wheels
    (including C extensions) are directly importable.

    ``spec``: ``["pkg==1.0", ...]`` or ``{"packages": [...],
    "find_links": dir}``. Offline installs (this runtime has no package
    egress) use ``find_links`` — a local wheel directory, also settable
    via ``RAY_TPU_PIP_FIND_LINKS`` — with ``--no-index``. The cache key
    covers the package list AND the wheel directory's content hash, so
    republishing a wheel rebuilds instead of serving the stale prefix.
    """
    import subprocess
    import sys as _sys

    if isinstance(spec, dict):
        packages = [str(p) for p in spec.get("packages", [])]
        find_links = spec.get("find_links")
    elif isinstance(spec, (list, tuple)):
        packages = [str(p) for p in spec]
        find_links = None
    else:
        raise RuntimeEnvError(
            f"pip spec must be a list or dict, got {type(spec).__name__}")
    if not packages:
        raise RuntimeEnvError("pip spec has no packages")
    find_links = find_links or os.environ.get("RAY_TPU_PIP_FIND_LINKS")
    h = hashlib.blake2b(digest_size=16)
    h.update(repr(sorted(packages)).encode())
    if find_links:
        if not os.path.isdir(find_links):
            raise RuntimeEnvError(
                f"pip find_links {find_links!r} is not a directory")
        h.update(_hash_path(find_links).encode())
    target = os.path.join(_CACHE_DIR, "pip", h.hexdigest())
    if os.path.isdir(target):
        # Lock-free fast path: a materialized prefix is immutable, and a
        # cache hit must not wait behind another env's minutes-long build.
        return target
    # check-then-build must be one critical section, or N concurrent
    # same-env tasks each run pip (observed: 3 builds for 3 tasks);
    # cross-PROCESS racers are handled by unique staging + atomic replace
    with _pip_build_lock(target):
        if os.path.isdir(target):
            return target  # built while we waited
        import tempfile
        os.makedirs(os.path.dirname(target), exist_ok=True)
        tmp = tempfile.mkdtemp(prefix=os.path.basename(target) + ".stage.",
                               dir=os.path.dirname(target))
        cmd = [_sys.executable, "-m", "pip", "install", "--target", tmp,
               "--no-cache-dir", "--disable-pip-version-check", "--quiet"]
        if find_links:
            cmd += ["--no-index", "--find-links", find_links]
        cmd += packages
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=600)
        except subprocess.TimeoutExpired as e:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeEnvError(
                f"pip install of {packages} timed out after 600s") from e
        if proc.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeEnvError(
                f"pip install of {packages} failed: {proc.stderr[-800:]}")
        if counter is not None:
            counter[0] += 1
        try:
            os.replace(tmp, target)
        except OSError:
            # concurrent materialization (other process) won; use its copy
            shutil.rmtree(tmp, ignore_errors=True)
    return target


class MaterializedEnv:
    """A staged environment ready to wrap task execution."""

    def __init__(self, env_vars: Dict[str, str],
                 sys_paths: List[str]):
        self.env_vars = env_vars
        self.sys_paths = sys_paths
        self.key = hashlib.blake2b(
            repr((sorted(env_vars.items()), sorted(sys_paths))).encode(),
            digest_size=12).hexdigest()

    @contextlib.contextmanager
    def applied(self):
        _ENV_GATE.enter(self)
        try:
            yield
        finally:
            _ENV_GATE.exit(self)


class RuntimeEnvManager:
    """Materializes and caches runtime envs (the runtime-env agent role,
    ``GetOrCreateRuntimeEnv`` ``runtime_env_agent.py:256``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: Dict[str, MaterializedEnv] = {}
        self.num_materialized = 0
        self._pip_builds = [0]  # boxed: _materialize_pip increments

    @property
    def num_pip_builds(self) -> int:
        return self._pip_builds[0]

    def get_or_create(self, runtime_env: Optional[Dict[str, Any]]
                      ) -> Optional[MaterializedEnv]:
        if not runtime_env:
            return None
        validate(runtime_env)
        # Stage first: staging is content-hashed, so the cache key reflects
        # the CURRENT file contents — editing working_dir and resubmitting
        # must pick up the new code, not a stale repr-keyed entry.
        sys_paths: List[str] = []
        # Order matters: the gate insert(0)s each path in turn, so LATER
        # entries shadow earlier ones — pip packages first (lowest
        # precedence), then working_dir, then py_modules.
        if "pip" in runtime_env:
            sys_paths.append(_materialize_pip(runtime_env["pip"],
                                              self._pip_builds))
        if "working_dir" in runtime_env:
            sys_paths.append(_stage(runtime_env["working_dir"]))
        for mod in runtime_env.get("py_modules", ()):
            sys_paths.append(_stage(mod))
        env_vars = dict(runtime_env.get("env_vars", {}))
        key = repr((sorted(env_vars.items()), sys_paths))
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                return hit
            env = MaterializedEnv(env_vars, sys_paths)
            self._cache[key] = env
            self.num_materialized += 1
            return env


_manager = RuntimeEnvManager()


def get_manager() -> RuntimeEnvManager:
    return _manager
