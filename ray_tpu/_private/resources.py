"""Cluster resource model with fixed-point arithmetic.

Parity with the reference's resource request/instance model
(``src/ray/raylet/scheduling/fixed_point.h``, ``cluster_resource_data.h``):
resource quantities are fixed-point (1e-4 granularity) so fractional CPUs/TPUs
never accumulate float drift. TPU is a first-class resource here (the
reference only knows NVIDIA GPUs — ``resource_spec.py:273-310``), including
per-topology labels like ``tpu-v5e-8`` usable as custom resources.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

RESOLUTION = 10000  # 1e-4 granularity, matching FixedPoint in the reference

CPU = "CPU"
TPU = "TPU"
GPU = "GPU"  # accepted for API compat; maps onto accelerator slots
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

PREDEFINED = (CPU, TPU, GPU, MEMORY, OBJECT_STORE_MEMORY)


def _fp(value: float) -> int:
    return round(value * RESOLUTION)


def _unfp(value: int) -> float:
    return value / RESOLUTION


class ResourceSet:
    """A bag of named resource quantities (fixed-point internally)."""

    __slots__ = ("_amounts",)

    def __init__(self, amounts: Optional[Dict[str, float]] = None):
        self._amounts: Dict[str, int] = {}
        if amounts:
            for name, qty in amounts.items():
                q = _fp(qty)
                if q < 0:
                    raise ValueError(f"negative resource {name}={qty}")
                if q > 0:
                    self._amounts[name] = q

    @classmethod
    def _from_fp(cls, amounts: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._amounts = {k: v for k, v in amounts.items() if v > 0}
        return rs

    def get(self, name: str) -> float:
        return _unfp(self._amounts.get(name, 0))

    def names(self) -> Iterable[str]:
        return self._amounts.keys()

    def is_empty(self) -> bool:
        return not self._amounts

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._amounts.get(k, 0) >= v for k, v in self._amounts.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._amounts)
        for k, v in other._amounts.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet._from_fp(out)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._amounts)
        for k, v in other._amounts.items():
            nv = out.get(k, 0) - v
            if nv < 0:
                raise ValueError(f"resource {k} would go negative")
            out[k] = nv
        return ResourceSet._from_fp(out)

    def to_dict(self) -> Dict[str, float]:
        return {k: _unfp(v) for k, v in self._amounts.items()}

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._amounts == other._amounts

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


def resources_from_options(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    num_gpus: Optional[float] = None,
    memory: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    default_cpus: float = 1.0,
) -> ResourceSet:
    """Build a task/actor resource request from user options."""
    amounts: Dict[str, float] = {}
    amounts[CPU] = default_cpus if num_cpus is None else num_cpus
    if num_tpus:
        amounts[TPU] = num_tpus
    if num_gpus:
        amounts[GPU] = num_gpus
    if memory:
        amounts[MEMORY] = memory
    if resources:
        for k, v in resources.items():
            if k in (CPU, TPU, GPU):
                raise ValueError(
                    f"Use num_cpus/num_tpus/num_gpus instead of resources[{k!r}]")
            amounts[k] = v
    return ResourceSet(amounts)


class NodeResources:
    """Total + available resources of one node, with instance accounting."""

    def __init__(self, total: ResourceSet):
        self.total = total
        self.available = ResourceSet._from_fp(dict(total._amounts))

    def can_fit(self, request: ResourceSet) -> bool:
        return request.is_subset_of(self.available)

    def could_ever_fit(self, request: ResourceSet) -> bool:
        return request.is_subset_of(self.total)

    def allocate(self, request: ResourceSet):
        self.available = self.available.subtract(request)

    def release(self, request: ResourceSet):
        self.available = self.available.add(request)
        # Guard against double-release pushing past total.
        for k, v in self.available._amounts.items():
            cap = self.total._amounts.get(k, 0)
            if v > cap:
                self.available._amounts[k] = cap

    def utilization(self) -> float:
        """Max utilization across requested dimensions, for hybrid scheduling."""
        best = 0.0
        for k, tot in self.total._amounts.items():
            if tot <= 0:
                continue
            used = tot - self.available._amounts.get(k, 0)
            best = max(best, used / tot)
        return best
