"""RPC substrate: length-prefixed protobuf frames over TCP.

The L1 layer (the reference's ``src/ray/rpc/`` gRPC wrappers, redesigned):
one socket per client→server direction carries multiplexed request/reply
frames matched by ``seq``, plus unsolicited server pushes (``seq=0``) for
pubsub. Long-running requests (task pushes) keep their seq open until the
work finishes — the reply IS the completion notification, so there is no
separate polling or callback channel (the reference needs PushTask +
reply + pubsub for the same round trip).

Wire format: ``4-byte big-endian length | Envelope protobuf`` — see
``ray_tpu/protocol/raytpu.proto``.
"""

from __future__ import annotations

import hmac
import logging
import os
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional, Tuple

from ray_tpu import chaos, observability
from ray_tpu._private.config import _config
from ray_tpu.observability import perf
from ray_tpu.protocol import pb

# raylint: hot-path  (payload plane: R8 flags hidden payload copies)
logger = logging.getLogger("ray_tpu")

# Runtime half of R19: under RAY_TPU_LOCKWATCH, synchronous RPC waits and
# handler executions become pseudo-lock sites (``rpc:<METHOD>``) in the
# lockwatch order graph, so a lock held across the wire closes the same
# CYCLE the static rule names. None (the default) keeps this a dead branch.
_lockwatch = None
if os.environ.get("RAY_TPU_LOCKWATCH"):
    from ray_tpu.devtools import lockwatch as _lockwatch

MAX_FRAME = 1 << 31  # 2 GiB hard cap per frame
_LEN = struct.Struct(">I")


def default_auth_token() -> Optional[bytes]:
    """The cluster's shared secret, if one is set for this process.

    Minted by the head node at cluster start (scripts/cluster.py) and
    distributed out-of-band (run-dir token file / env) like the
    reference's redis password. Every daemon/state connection must open
    with it — an unauthenticated socket that can reach a daemon is
    remote code execution by design (PUSH_TASK carries cloudpickle)."""
    tok = os.environ.get("RAY_TPU_AUTH_TOKEN")
    return tok.encode() if tok else None


class RpcConnectionError(ConnectionError):
    pass


def _method_name(method: int) -> str:
    return (pb.Method.Name(method) if method in pb.Method.values()
            else str(method))


class RpcRemoteError(RuntimeError):
    """The peer's handler raised; message carries the remote error string."""


def _read_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes with recv_into — no per-chunk allocation
    or extend-copy (multi-MB fetch replies ride this path)."""
    buf = bytearray(n)
    recv_into_exact(sock, memoryview(buf))
    return buf


PRE_AUTH_MAX_FRAME = 1 << 16  # before auth, only a tiny AUTH frame is legal


def read_frame(sock: socket.socket,
               max_len: int = MAX_FRAME) -> pb.Envelope:
    (length,) = _LEN.unpack(_read_exact(sock, 4))
    if length > max_len:
        raise RpcConnectionError(f"frame too large: {length}")
    env = pb.Envelope()
    env.ParseFromString(_read_exact(sock, length))
    return env


def frame_bytes(env: pb.Envelope) -> bytes:
    payload = env.SerializeToString()
    return _LEN.pack(len(payload)) + payload


_IOV_GROUP = 512  # stay under IOV_MAX (1024 on Linux) per sendmsg


def _sendmsg_all(sock: socket.socket, pieces: list) -> None:
    """Drain a gather list fully (sendmsg may stop at any boundary)."""
    while pieces:
        sent = sock.sendmsg(pieces[:_IOV_GROUP])
        while pieces and sent >= len(pieces[0]):
            sent -= len(pieces[0])
            pieces.pop(0)
        if pieces and sent:
            pieces[0] = pieces[0][sent:]


def send_frame(sock: socket.socket, env: pb.Envelope,
               raw=None) -> None:
    """Write one frame with scatter-gather IO: the length prefix and the
    serialized envelope go out in one sendmsg, WITHOUT concatenating (the
    concat would copy every multi-MB payload a second time).

    ``raw`` rides the bulk lane: ``env.raw_len`` announces it, and its
    bytes follow the envelope frame in the SAME gather write — zero
    user-space copies of the payload on this side, and the receiver
    recv_into's it straight into its destination buffer. ``raw`` may be
    one bytes-like OR a list/tuple of bytes-likes: a scattered payload
    (e.g. pickle-5 out-of-band buffers still living in their source
    arrays) ships without ever being assembled contiguously."""
    raw_mvs = []
    if raw is not None:
        # byte-cast FIRST: len() of a structured memoryview counts
        # ELEMENTS of its first dimension, not bytes
        if isinstance(raw, (list, tuple)):
            raw_mvs = [memoryview(r).cast("B") for r in raw]
        else:
            raw_mvs = [memoryview(raw).cast("B")]
        env.raw_len = sum(len(mv) for mv in raw_mvs)
    payload = env.SerializeToString()
    pieces = [memoryview(_LEN.pack(len(payload))), memoryview(payload)]
    pieces.extend(mv for mv in raw_mvs if len(mv))
    _sendmsg_all(sock, pieces)


def recv_into_exact(sock: socket.socket, mv: memoryview) -> None:
    got, n = 0, len(mv)
    while got < n:
        r = sock.recv_into(mv[got:], n - got)
        if r == 0:
            raise RpcConnectionError("connection closed by peer")
        got += r


def _set_sock_bufs(sock: socket.socket, nbytes: int) -> None:
    """Best-effort SO_SNDBUF/SO_RCVBUF sizing (kernel clamps silently)."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, nbytes)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, nbytes)
    except OSError as e:
        logger.debug("socket buffer sizing failed: %s", e)


class _Pending:
    __slots__ = ("event", "env", "callback", "raw_sink")

    def __init__(self):
        self.event = threading.Event()
        self.env: Optional[pb.Envelope] = None
        self.callback = None
        self.raw_sink = None  # fn(length) -> writable memoryview


class RpcClient:
    """One outgoing connection; thread-safe calls multiplexed by seq."""

    def __init__(self, address: str, connect_timeout: Optional[float] = None,
                 on_push: Optional[Callable[[pb.Envelope], None]] = None,
                 on_close: Optional[Callable[[Exception], None]] = None,
                 auth_token: Optional[bytes] = None,
                 sock_buf_bytes: int = 0):
        host, port = address.rsplit(":", 1)
        self.address = address
        if connect_timeout is None:
            connect_timeout = _config.get("rpc_connect_timeout_s")
        _t0 = time.monotonic() if perf.ENABLED else 0.0
        try:
            if chaos.ENABLED:
                chaos.inject("rpc.client.connect", peer=address)
            self._sock = socket.create_connection((host, int(port)),
                                                  timeout=connect_timeout)
        except OSError as e:
            raise RpcConnectionError(
                f"connect to {address} failed: {e}") from e
        try:
            if _t0:
                perf.observe("rpc.connect", (time.monotonic() - _t0) * 1e3)
            self._sock.settimeout(None)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if sock_buf_bytes > 0:
                # Data-plane connections size their kernel buffers to the
                # transfer chunk so one chunk stays in flight per stream
                # (defaults keep the first RTTs window-limited). Linux
                # auto-tunes past the initial SO_RCVBUF only when it is NOT
                # set explicitly, so this is opt-in per connection.
                _set_sock_bufs(self._sock, sock_buf_bytes)
            token = (auth_token if auth_token is not None
                     else default_auth_token())
            if token:
                # First frame of every connection: prove membership. The
                # server closes the socket on mismatch; the caller surfaces
                # that as a connection error on its first real call.
                try:
                    self._sock.sendall(frame_bytes(pb.Envelope(
                        seq=0, method=pb.AUTH, body=token)))
                except OSError as e:
                    raise RpcConnectionError(
                        f"auth handshake to {address} failed: {e}") from e
            self._wlock = threading.Lock()
            self._pending_lock = threading.Lock()
            self._pending: Dict[int, _Pending] = {}  # raylint: guarded-by(self._pending_lock)
            self._seq = 0  # raylint: guarded-by(self._pending_lock)
            self._on_push = on_push
            self._on_close = on_close
            self._closed = False
            self._close_exc: Optional[Exception] = None
            self._reader = threading.Thread(
                target=self._read_loop, daemon=True,
                name=f"rpc-client-{address}")
            self._reader.start()
        except Exception:
            # Constructor aborts after the connect must not strand the fd.
            try:
                self._sock.close()
            except OSError:
                pass
            raise

    # -- public ---------------------------------------------------------------

    def call(self, method: int, body: bytes = b"",
             timeout: Optional[float] = None,
             raw_sink=None, raw=None) -> pb.Envelope:
        """Send a request, block for its reply. Raises RpcRemoteError on a
        handler error, RpcConnectionError if the connection dies first.
        ``raw_sink(length) -> memoryview``: where to land the reply's
        bulk-lane bytes, filled before this returns (the caller keeps its
        own reference to the buffer the sink handed out). ``raw``:
        bulk-lane payload to ship WITH the request (gather-write, no
        protobuf copy)."""
        if timeout is None:
            # rpc_call_deadline_s=0 (the default) keeps unbounded waits:
            # task-push replies land at task completion, which can be
            # arbitrarily far out.
            default = _config.get("rpc_call_deadline_s")
            if default > 0:
                timeout = default
        pending = _Pending()
        pending.raw_sink = raw_sink
        with self._pending_lock:
            if self._closed:
                raise RpcConnectionError(
                    f"connection to {self.address} is closed: {self._close_exc}")
            self._seq += 1
            seq = self._seq
            self._pending[seq] = pending
        env = pb.Envelope(seq=seq, method=method, body=body)
        t0 = 0.0
        if observability.ENABLED:
            tctx = observability.wire_context()
            if tctx:
                env.trace = tctx
        if perf.ENABLED:
            t0 = time.monotonic()
        if _lockwatch is not None and _lockwatch.installed():
            _lockwatch.rpc_client_wait(f"rpc:{_method_name(method)}")
        try:
            self._send(env, raw=raw)
            if not pending.event.wait(timeout):
                raise TimeoutError(
                    f"rpc {pb.Method.Name(method)} to {self.address} timed out")
        finally:
            with self._pending_lock:
                self._pending.pop(seq, None)
        reply = pending.env
        if reply is None:
            raise RpcConnectionError(
                f"connection to {self.address} lost mid-call: {self._close_exc}")
        if reply.error:
            raise RpcRemoteError(reply.error)
        if t0:
            perf.observe("rpc.call", (time.monotonic() - t0) * 1e3)
        return reply

    def call_async(self, method: int, body: bytes,
                   callback: Callable[[Optional[pb.Envelope],
                                       Optional[Exception]], None],
                   raw_sink=None, raw=None) -> None:
        """Fire a request; invoke ``callback(reply, None)`` or
        ``callback(None, error)`` from the reader thread when done.
        ``raw_sink`` as in :meth:`call` — filled before the callback.
        ``raw``: bulk-lane payload (one bytes-like or a gather list)
        shipped with the request, no protobuf copy."""
        tctx = ""
        if observability.ENABLED:
            tctx = observability.wire_context()
        if perf.ENABLED:
            _t0, _cb = time.monotonic(), callback

            def callback(env, error, _cb=_cb, _t0=_t0):
                perf.observe("rpc.call", (time.monotonic() - _t0) * 1e3)
                _cb(env, error)

        pending = _Pending()
        pending.callback = callback  # type: ignore[attr-defined]
        pending.raw_sink = raw_sink
        with self._pending_lock:
            if self._closed:
                callback(None, RpcConnectionError(
                    f"connection to {self.address} is closed"))
                return
            self._seq += 1
            seq = self._seq
            self._pending[seq] = pending
        env = pb.Envelope(seq=seq, method=method, body=body)
        if tctx:
            env.trace = tctx
        try:
            self._send(env, raw=raw)
        except Exception as e:
            with self._pending_lock:
                self._pending.pop(seq, None)
            callback(None, e)

    def call_burst(self, items, callback) -> None:
        """Ship MANY small requests in ONE gather write (one syscall, one
        chaos site, one lock acquisition) — the control-plane batching
        primitive. ``items``: list of ``(method, body)``;
        ``callback(index, reply_env, error)`` fires per item from the
        reader thread as the peer answers each seq. Frames go out in list
        order on this single connection, so a peer that processes frames
        per-connection in order (the state service's epoll loop) observes
        the ops in exactly the order they were enqueued."""
        pendings = []
        with self._pending_lock:
            if self._closed:
                err = RpcConnectionError(
                    f"connection to {self.address} is closed")
                for i in range(len(items)):
                    callback(i, None, err)
                return
            for i, _ in enumerate(items):
                self._seq += 1
                pending = _Pending()
                pending.callback = (
                    lambda env, error, _i=i: callback(_i, env, error))
                self._pending[self._seq] = pending
                pendings.append(self._seq)
        # Tiny control bodies: one contiguous buffer beats a long iovec.
        tctx = observability.wire_context() if observability.ENABLED else ""
        buf = bytearray()
        for seq, (method, body) in zip(pendings, items):
            env = pb.Envelope(seq=seq, method=method, body=body)
            if tctx:
                env.trace = tctx
            payload = env.SerializeToString()
            buf += _LEN.pack(len(payload))
            buf += payload
        try:
            self._send_bytes(buf)
        except Exception as e:
            self.fail_pending(pendings, e)

    def send_oneway(self, method: int, body: bytes = b"") -> None:
        env = pb.Envelope(seq=0, method=method, body=body)
        if observability.ENABLED:
            tctx = observability.wire_context()
            if tctx:
                env.trace = tctx
        self._send(env)

    def allocate_pending(self, callback) -> int:
        """Reserve a reply seq with a callback but send NOTHING — the
        caller ships the seq inside a batch envelope (TaskBatchMsg) and
        the peer answers it like any ordinary reply. Pair with
        fail_pending when the batch send errors."""
        pending = _Pending()
        pending.callback = callback
        with self._pending_lock:
            if self._closed:
                raise RpcConnectionError(
                    f"connection to {self.address} is closed")
            self._seq += 1
            seq = self._seq
            self._pending[seq] = pending
        return seq

    def fail_pending(self, seqs, error: Exception) -> None:
        """Settle reserved seqs whose batch never reached the wire."""
        if (isinstance(error, RpcConnectionError)
                and self.address not in str(error)):
            error = RpcConnectionError(
                f"connection to {self.address}: {error}")
        for seq in seqs:
            with self._pending_lock:
                pending = self._pending.pop(seq, None)
            if pending is not None and pending.callback is not None:
                try:
                    pending.callback(None, error)
                except Exception:
                    logger.exception("rpc callback failed")

    def close(self):
        self._shutdown(RpcConnectionError("closed locally"))

    def join_reader(self, timeout: Optional[float] = None) -> None:
        """Wait for the reader thread to exit (after close): once it has,
        no raw sink handed to this connection can be written again —
        required before reclaiming a sink's destination buffer."""
        if self._reader is not threading.current_thread():
            self._reader.join(timeout)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- internals ------------------------------------------------------------

    def _send(self, env: pb.Envelope, raw=None):
        if chaos.ENABLED:
            try:
                act = chaos.inject("rpc.client.send", peer=self.address,
                                   method=_method_name(env.method))
            except chaos.ChaosConnectionReset as e:
                # A real peer reset kills the whole connection, not one
                # frame — tear down so pending calls fail like the wire did.
                self._shutdown(e)
                raise RpcConnectionError(
                    f"send to {self.address} failed: {e}") from e
            if act == "drop":
                return  # frame "lost on the wire"; the caller times out
        with self._wlock:
            try:
                send_frame(self._sock, env, raw=raw)
            except OSError as e:
                raise RpcConnectionError(
                    f"send to {self.address} failed: {e}") from e

    def _send_bytes(self, buf) -> None:
        """Pre-framed burst write (call_burst); same chaos semantics as
        _send — a reset kills the connection, a drop loses the burst."""
        if chaos.ENABLED:
            try:
                act = chaos.inject("rpc.client.send", peer=self.address,
                                   method="BURST")
            except chaos.ChaosConnectionReset as e:
                self._shutdown(e)
                raise RpcConnectionError(
                    f"send to {self.address} failed: {e}") from e
            if act == "drop":
                return
        with self._wlock:
            try:
                self._sock.sendall(buf)
            except OSError as e:
                raise RpcConnectionError(
                    f"send to {self.address} failed: {e}") from e

    def _read_loop(self):
        try:
            while True:
                env = read_frame(self._sock)
                if chaos.ENABLED:
                    # reset raises -> caught below -> _shutdown, exactly a
                    # mid-stream peer reset; drop discards the frame (after
                    # draining its bulk lane to keep framing intact).
                    if chaos.inject("rpc.client.recv",
                                    peer=self.address) == "drop":
                        if env.raw_len:
                            _read_exact(self._sock, env.raw_len)
                        continue
                raw_pending = None
                if env.raw_len:
                    if env.raw_len > MAX_FRAME:
                        raise RpcConnectionError(
                            f"raw payload too large: {env.raw_len}")
                    with self._pending_lock:
                        raw_pending = self._pending.get(env.seq)
                    sink = (raw_pending.raw_sink
                            if raw_pending is not None else None)
                    mv = None
                    if sink is not None:
                        try:
                            mv = sink(env.raw_len)
                        except Exception:
                            logger.exception("raw sink failed")
                    if mv is not None and len(mv) == env.raw_len:
                        recv_into_exact(self._sock, memoryview(mv))
                    else:
                        # No usable sink: drain to keep framing intact.
                        _read_exact(self._sock, env.raw_len)
                if env.seq == 0 and not env.reply:
                    if self._on_push is not None:
                        try:
                            self._on_push(env)
                        except Exception:
                            logger.exception("push handler failed")
                    continue
                with self._pending_lock:
                    pending = self._pending.get(env.seq)
                if pending is None:
                    continue
                pending.env = env
                cb = getattr(pending, "callback", None)
                if cb is not None:
                    with self._pending_lock:
                        self._pending.pop(env.seq, None)
                    err = RpcRemoteError(env.error) if env.error else None
                    try:
                        cb(None if err else env, err)
                    except Exception:
                        logger.exception("rpc callback failed")
                else:
                    pending.event.set()
        except Exception as e:  # noqa: BLE001 — connection teardown
            self._shutdown(e)

    def _shutdown(self, exc: Exception):
        with self._pending_lock:
            if self._closed:
                return
            self._closed = True
            self._close_exc = exc  # raylint: allow(data-race) set under _pending_lock before pending events fire; post-wait readers see it via the event's happens-before edge
            pending, self._pending = dict(self._pending), {}
        try:
            self._sock.close()
        except OSError:
            pass
        err = RpcConnectionError(
            f"connection to {self.address} lost: {exc}")
        for p in pending.values():
            cb = getattr(p, "callback", None)
            if cb is not None:
                try:
                    cb(None, err)
                except Exception:
                    logger.exception("rpc callback failed on close")
            else:
                p.event.set()  # p.env stays None -> caller raises
        if self._on_close is not None:
            try:
                self._on_close(exc)
            except Exception:
                logger.exception("on_close handler failed")


class RpcContext:
    """Handed to server handlers; reply now or later (from any thread)."""

    def __init__(self, server: "RpcServer", sock: socket.socket,
                 wlock: threading.Lock, env: pb.Envelope):
        self._sock = sock
        self._wlock = wlock
        self.method = env.method
        self.seq = env.seq
        self.body = env.body
        self.trace = env.trace  # caller's "trace_id:span_id", or ""
        self.raw = None  # bulk-lane bytes of the REQUEST, if any
        self.peer = None  # set by server
        self._done = False

    def reply(self, body: bytes = b"", raw=None):
        """``raw``: bulk-lane payload (bytes-like); ships after the
        envelope via gather-write — no protobuf copy of the bulk."""
        self._reply(pb.Envelope(seq=self.seq, method=self.method,
                                reply=True, body=body), raw=raw)

    def child(self, seq: int, method: int, body: bytes = b""
              ) -> "RpcContext":
        """A sibling context on the SAME connection with its own reply
        seq — how one batch envelope fans out into per-item contexts
        whose replies multiplex like ordinary calls."""
        env = pb.Envelope(seq=seq, method=method, body=body)
        ctx = RpcContext(None, self._sock, self._wlock, env)
        ctx.conn_id = getattr(self, "conn_id", None)
        ctx.trace = self.trace  # batch items inherit the batch's context
        return ctx

    def reply_error(self, message: str):
        self._reply(pb.Envelope(seq=self.seq, method=self.method,
                                reply=True, error=message))

    def push(self, method: int, body: bytes):
        """Unsolicited push to this connection (pubsub delivery)."""
        with self._wlock:
            send_frame(self._sock,
                       pb.Envelope(seq=0, method=method, body=body))

    def _reply(self, env: pb.Envelope, raw=None):
        if self._done:
            return
        self._done = True
        if chaos.ENABLED:
            try:
                act = chaos.inject("rpc.server.send",
                                   method=_method_name(self.method))
            except chaos.ChaosConnectionReset:
                # kill the connection instead of replying: the client sees
                # a reset with this request in flight
                try:
                    self._sock.close()
                except OSError:
                    pass
                return
            if act == "drop":
                return  # reply "lost on the wire"; the caller times out
        try:
            with self._wlock:
                send_frame(self._sock, env, raw=raw)
        except OSError:
            pass  # caller vanished; nothing to do


Handler = Callable[[RpcContext], None]


class RpcServer:
    """Threaded frame server. The handler receives an RpcContext and MUST
    eventually call ctx.reply()/ctx.reply_error() (possibly from another
    thread — that is how task pushes defer their reply to completion)."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1",
                 port: int = 0, max_workers: int = 64,
                 inline_methods: Optional[set] = None,
                 auth_token: Optional[bytes] = None,
                 sock_buf_bytes: int = 0):
        self._handler = handler
        self._auth_token = (auth_token if auth_token is not None
                            else default_auth_token())
        self._sock_buf_bytes = sock_buf_bytes
        # Methods handled synchronously on the connection's reader thread:
        # cheap enqueue-style handlers that need per-connection ordering
        # (actor mailbox inserts — the reference's actor sequencing queues,
        # transport/actor_scheduling_queue.cc). Everything else runs in the
        # worker pool.
        self._inline = inline_methods or set()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._pool = None
        try:
            self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._lsock.bind((host, port))
            self._lsock.listen(128)
            self.host, self.port = self._lsock.getsockname()
            self.address = f"{self.host}:{self.port}"
            self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                            thread_name_prefix="rpc-srv")
            self._conns: Dict[int, Tuple[socket.socket, threading.Lock]] = {}  # raylint: guarded-by(self._conn_lock)
            self._conn_lock = threading.Lock()
            self._closed = False
            self._quiesced = False
            self._on_disconnect: Optional[Callable[[int], None]] = None
            self._accept_thread = threading.Thread(
                target=self._accept_loop, daemon=True,
                name=f"rpc-accept-{self.port}")
            self._accept_thread.start()
        except Exception:
            # bind() on a taken port (EADDRINUSE) is the common abort here;
            # without this the listener fd leaks on every retry.
            try:
                self._lsock.close()
            except OSError:
                pass
            if self._pool is not None:
                self._pool.shutdown(wait=False)
            raise

    def set_on_disconnect(self, cb: Callable[[int], None]):
        self._on_disconnect = cb  # raylint: allow(data-race) callback installed once during server wiring before serving starts

    def quiesce(self):
        """Stop accepting NEW connections while established ones (and the
        worker pool) keep running: in-flight requests finish and reply
        normally. First phase of a graceful drain; ``close()`` stays the
        hard stop."""
        self._quiesced = True
        try:
            self._lsock.close()
        except OSError:
            pass

    def close(self):
        self._closed = True
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for sock, _ in conns:
            try:
                sock.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _accept_loop(self):
        conn_id = 0
        while not self._closed:
            try:
                sock, _addr = self._lsock.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._sock_buf_bytes > 0:
                _set_sock_bufs(sock, self._sock_buf_bytes)
            conn_id += 1
            wlock = threading.Lock()
            with self._conn_lock:
                self._conns[conn_id] = (sock, wlock)
            t = threading.Thread(target=self._conn_loop,
                                 args=(conn_id, sock, wlock), daemon=True,
                                 name=f"rpc-conn-{self.port}-{conn_id}")
            t.start()

    def _conn_loop(self, conn_id: int, sock: socket.socket,
                   wlock: threading.Lock):
        try:
            if self._auth_token:
                # Constant-time check of the connection's opening frame;
                # anything else (wrong token, other method, garbage) drops
                # the socket before a single byte reaches the handler.
                # Pre-auth frames are capped small so an unauthenticated
                # peer cannot make us buffer up to MAX_FRAME.
                env = read_frame(sock, max_len=PRE_AUTH_MAX_FRAME)
                if env.method != pb.AUTH or not hmac.compare_digest(
                        bytes(env.body), self._auth_token):
                    logger.warning("rejected unauthenticated connection")
                    return
            while True:
                env = read_frame(sock)
                raw = None
                if env.raw_len:
                    if env.raw_len > MAX_FRAME:
                        raise RpcConnectionError(
                            f"raw payload too large: {env.raw_len}")
                    raw = _read_exact(sock, env.raw_len)
                if chaos.ENABLED:
                    # reset raises -> finally below closes the socket, the
                    # server-side version of a mid-request peer reset
                    if chaos.inject("rpc.server.recv", conn=str(conn_id),
                                    method=_method_name(env.method)) == "drop":
                        continue  # request "never arrived"
                if env.method == pb.AUTH:
                    continue  # redundant re-auth: ignore
                ctx = RpcContext(self, sock, wlock, env)
                ctx.raw = raw
                ctx.conn_id = conn_id
                if env.method in self._inline:
                    self._run_handler(ctx)
                else:
                    self._pool.submit(self._run_handler, ctx)
        except Exception as e:  # noqa: BLE001 — normal disconnect path
            logger.debug("reader loop ended: %s", e)
        finally:
            with self._conn_lock:
                self._conns.pop(conn_id, None)
            try:
                sock.close()
            except OSError:
                pass
            if self._on_disconnect is not None:
                try:
                    self._on_disconnect(conn_id)
                except Exception:
                    logger.exception("on_disconnect failed")

    def _run_handler(self, ctx: RpcContext):
        # Adopt the caller's trace context around dispatch so spans the
        # handler opens (fetch, task execute, ...) join the caller's tree.
        token = None
        if observability.ENABLED and ctx.trace:
            token = observability.adopt_wire(ctx.trace)
        lw_token = None
        if _lockwatch is not None and _lockwatch.installed():
            lw_token = _lockwatch.rpc_handler_enter(
                f"rpc:{_method_name(ctx.method)}")
        try:
            if token is not None:
                with observability.span(f"rpc:{_method_name(ctx.method)}",
                                        cat="rpc"):
                    self._handler(ctx)
            else:
                self._handler(ctx)
        except Exception as e:  # noqa: BLE001 — report to caller
            logger.exception("rpc handler error for %s",
                             pb.Method.Name(ctx.method)
                             if ctx.method in pb.Method.values() else ctx.method)
            ctx.reply_error(f"{type(e).__name__}: {e}")
        finally:
            if lw_token is not None:
                _lockwatch.rpc_handler_exit(lw_token)
            if token is not None:
                observability.reset(token)


class ConnectionPool:
    """Shared per-process outgoing connections, keyed by address."""

    def __init__(self):
        self._lock = threading.Lock()
        self._clients: Dict[str, RpcClient] = {}  # raylint: guarded-by(self._lock)

    def get(self, address: str,
            on_close: Optional[Callable[[str, Exception], None]] = None
            ) -> RpcClient:
        with self._lock:
            client = self._clients.get(address)
            if client is not None and not client.closed:
                return client

            def _closed(exc: Exception, _addr=address):
                with self._lock:
                    cur = self._clients.get(_addr)
                    if cur is not None and cur.closed:
                        del self._clients[_addr]
                if on_close is not None:
                    on_close(_addr, exc)

            client = RpcClient(address, on_close=_closed)
            self._clients[address] = client
            return client

    def drop(self, address: str):
        with self._lock:
            client = self._clients.pop(address, None)
        if client is not None:
            client.close()

    def close_all(self):
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()
