"""Cross-host clock-offset estimation against the state-service clock.

Every daemon (and the driver) already heartbeats the state service; since
PR 14 the ack carries ``server_time_ms`` — the service wall clock at reply
time. Pairing that beacon with the local send/recv stamps gives an
NTP-style sample: assuming the network path is roughly symmetric, the
server's stamp corresponds to the request's *midpoint*, so

    offset = (t_send + t_recv) / 2 - server_time

estimates this process's wall-clock lead over the service clock. Samples
taken under congestion are the noisy ones, so the estimator keeps a short
window and trusts the **lowest-RTT** sample in it (the classic NTP clock
filter): a fast round trip bounds the asymmetry error by rtt/2.

The offset makes cross-host latency spans meaningful: ``task.e2e`` compares
a submit stamp from one host against an execute stamp on another. The
submitter rebases its stamp to the service timebase (``to_server_s``) when
the spec crosses a process boundary and the executor rebases it back to
its own clock (``to_local_s``); with both hosts synced to the same beacon
the residual error is bounded by the two heartbeat RTTs instead of by raw
NTP drift between hosts. The current estimate is exported as the
``clock_skew_ms`` gauge so doctor/top can spot a host whose clock walks.

Fast path mirrors perf/goodput: ``ENABLED`` is a module bool read from the
``clock_sync_enabled`` config knob; everything is a no-op (offset 0.0)
when off or before the first sample.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from ray_tpu._private.config import _config

ENABLED = bool(_config.get("clock_sync_enabled"))

# NTP clock-filter window: enough beats (~8-16s at the default heartbeat
# interval) to ride out one congested burst, small enough to track a
# stepped clock within a few beats.
_WINDOW = 16

_lock = threading.Lock()
_samples: deque = deque(maxlen=_WINDOW)  # (rtt_s, offset_s)  # raylint: guarded-by(_lock)
_offset_s = 0.0
_synced = False
_gauge = None


def _skew_gauge():
    global _gauge
    if _gauge is None:
        from ray_tpu.util import metrics as _metrics
        # raylint: allow(data-race) idempotent lazy gauge init; the metrics registry dedups by name
        _gauge = _metrics.Gauge(
            "clock_skew_ms",
            "estimated local wall-clock lead over the state-service clock "
            "(NTP-style, lowest-RTT heartbeat sample wins)")
    return _gauge


def observe(t_send_s: float, t_recv_s: float, server_time_s: float):
    """Feed one heartbeat exchange: local send/recv stamps (time.time())
    and the service's ``server_time_ms / 1e3`` beacon. ``server_time_s``
    <= 0 means the service predates the field — ignored."""
    global _offset_s, _synced
    if not ENABLED or server_time_s <= 0.0:
        return
    rtt = t_recv_s - t_send_s
    if rtt < 0.0:  # local clock stepped mid-exchange; sample is garbage
        return
    offset = (t_send_s + t_recv_s) / 2.0 - server_time_s
    with _lock:
        _samples.append((rtt, offset))
        # Lowest-RTT sample in the window is the least asymmetric one.
        _offset_s = min(_samples)[1]  # raylint: guarded-by(_lock)
        _synced = True
        est_ms = _offset_s * 1e3
    _skew_gauge().set(est_ms)


def offset_s() -> float:
    """Estimated local-clock lead over the service clock (0.0 until the
    first beacon lands)."""
    with _lock:
        return _offset_s


def synced() -> bool:
    with _lock:
        return _synced


def to_server_s(local_s: float) -> float:
    """Rebase a local time.time() stamp onto the service timebase."""
    return local_s - offset_s()


def to_local_s(server_s: float) -> float:
    """Rebase a service-timebase stamp onto this process's clock."""
    return server_s + offset_s()


def reset():
    """Forget all samples (tests / fork)."""
    global _offset_s, _synced
    with _lock:
        _samples.clear()
        _offset_s = 0.0
        _synced = False
