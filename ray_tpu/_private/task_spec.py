"""Task specifications — the unit the scheduler and lineage table operate on.

Parity with ``TaskSpecification`` (``src/ray/common/task/task_spec.h``) and
the option registry (``python/ray/_private/ray_option_utils.py``).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.resources import ResourceSet


@dataclass
class SchedulingStrategy:
    """Base; concrete strategies live in ray_tpu.util.scheduling_strategies."""


@dataclass
class TaskOptions:
    num_returns: int = 1
    resources: ResourceSet = field(default_factory=ResourceSet)
    max_retries: int = 3
    retry_exceptions: Any = False  # False | True | list of exception types
    scheduling_strategy: Any = "DEFAULT"
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    name: Optional[str] = None
    runtime_env: Optional[Dict[str, Any]] = None
    concurrency_group: Optional[str] = None
    _generator: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function: Callable
    function_name: str
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]
    options: TaskOptions
    return_ids: Tuple[ObjectID, ...] = ()
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    attempt: int = 0
    # Cross-task trace propagation (reference
    # ``python/ray/util/tracing/tracing_helper.py:160-175``): the trace
    # id rides every hop of a task tree; parent_span_id links this
    # task's span to the span that submitted it.
    trace_id: str = ""
    parent_span_id: str = ""
    # Perf plane: wall-clock submit stamp (time.time(), set only when
    # perf.ENABLED) so the executing side can split scheduling wait from
    # execution in the task.e2e / task.sched histograms.  Wall clock
    # because submit and execute may be different processes; when the spec
    # crosses a process boundary the stamp is rebased through the
    # state-service timebase (clocksync) so the execute-site delta is
    # skew-corrected, and residual negatives clamp to the execution time.
    perf_submit_s: float = 0.0

    def is_actor_task(self) -> bool:
        return self.actor_id is not None

    def retries_left(self) -> int:
        return self.options.max_retries - self.attempt

    def should_retry(self, error: BaseException) -> bool:
        if self.retries_left() <= 0:
            return False
        re = self.options.retry_exceptions
        # System-level failures (worker/node death) always honor max_retries;
        # application exceptions only when retry_exceptions allows them
        # (reference: _raylet.pyx:1581-1601).
        from ray_tpu.exceptions import NodeDiedError, WorkerCrashedError
        if isinstance(error, (WorkerCrashedError, NodeDiedError)):
            return True
        if re is True:
            return True
        if re is False or re is None:
            return False
        return isinstance(error, tuple(re))
